"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Design constraints (the hot paths this feeds are the transport's per-message
loop and the worker's per-chunk handlers):

- **lock-cheap**: no locks at all. Every mutation is a single attribute
  store or in-place add on a Python int/float — atomic under the GIL, and
  the control plane is single-threaded asyncio besides. Cross-thread
  readers (the flight-recorder signal handler) can only ever see a
  consistent previous value, never a torn one.
- **allocation-free on the hot path**: ``Counter.inc``/``Gauge.set`` touch
  one slot; ``Histogram.observe`` walks a small tuple of precomputed
  bounds. Metric objects are created once (module import / first use) and
  cached by name — ``counter("x")`` in a loop is a dict hit, but callers
  on hot paths should hold the object.
- **snapshot-to-dict**: ``Registry.snapshot()`` returns one flat
  JSON-ready dict, so any JSONL sink (``MetricsLogger.log_snapshot``, the
  flight recorder, bench_suite records) gets the whole registry for free.

Naming convention (OBSERVABILITY.md): dotted ``<layer>.<noun>[.<detail>]``
— e.g. ``transport.dropped.no_route``, ``worker.rounds_completed``,
``master.round_latency_s``. Seconds-valued metrics end in ``_s``.
"""

from __future__ import annotations

import time
from typing import Any, Callable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Series",
    "Registry",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "series",
]


class Counter:
    """Monotonic accumulator (int or float increments)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0

    def inc(self, n: int | float = 1) -> None:
        self.value += n

    add = inc  # alias for float-valued accumulation (e.g. seconds)


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value: int | float = 0

    def set(self, v: int | float) -> None:
        self.value = v

    def inc(self, n: int | float = 1) -> None:
        self.value += n

    def dec(self, n: int | float = 1) -> None:
        self.value -= n


class Histogram:
    """Fixed-bucket histogram: bounds are set at creation, observe() walks
    them (no allocation, no resizing — predictable hot-path cost)."""

    __slots__ = ("name", "bounds", "counts", "total", "count")

    #: default bounds suit latencies in seconds (100us .. 100s, log-ish)
    DEFAULT_BOUNDS = (
        1e-4, 3e-4, 1e-3, 3e-3, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0,
        100.0,
    )

    def __init__(self, name: str, bounds: tuple[float, ...] | None = None) -> None:
        self.name = name
        self.bounds = tuple(bounds) if bounds is not None else self.DEFAULT_BOUNDS
        if any(b >= a for b, a in zip(self.bounds, self.bounds[1:])):
            raise ValueError(f"histogram bounds must increase: {self.bounds}")
        self.counts = [0] * (len(self.bounds) + 1)  # last = overflow
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        i = 0
        bounds = self.bounds
        n = len(bounds)
        while i < n and v > bounds[i]:
            i += 1
        self.counts[i] += 1
        self.total += v
        self.count += 1

    def as_dict(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count if self.count else 0.0,
            "buckets": {
                (f"le_{b:g}" if i < len(self.bounds) else "inf"): c
                for i, (b, c) in enumerate(
                    zip((*self.bounds, float("inf")), self.counts)
                )
            },
        }


class Series:
    """Bounded list of structured events (e.g. re-mesh records): the
    registry's answer to ad-hoc ``events.append({...})`` bookkeeping —
    whoever reads the registry sees exactly what the producer recorded."""

    __slots__ = ("name", "maxlen", "values", "dropped")

    def __init__(self, name: str, maxlen: int = 1024) -> None:
        self.name = name
        self.maxlen = maxlen
        self.values: list[Any] = []
        self.dropped = 0

    def append(self, value: Any) -> None:
        if len(self.values) >= self.maxlen:
            self.dropped += 1  # bounded: never silently unbounded memory
            return
        self.values.append(value)


class Registry:
    """Name -> metric, get-or-create, plus pull-time collectors.

    A *collector* is a zero-arg callable returning a dict merged into every
    ``snapshot()`` — how per-instance state (e.g. each transport's
    ``stage_seconds``) joins the registry without paying a registry write
    on its hot path.
    """

    def __init__(self) -> None:
        self._metrics: dict[str, Any] = {}
        self._collectors: list[Callable[[], dict[str, Any]]] = []

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, *args)
        elif type(m) is not cls:
            raise TypeError(
                f"metric {name!r} already registered as {type(m).__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self, name: str, bounds: tuple[float, ...] | None = None
    ) -> Histogram:
        return self._get(name, Histogram, bounds)

    def series(self, name: str, maxlen: int = 1024) -> Series:
        return self._get(name, Series, maxlen)

    def register_collector(self, fn: Callable[[], dict[str, Any]]) -> None:
        self._collectors.append(fn)

    def snapshot(self) -> dict[str, Any]:
        """One flat JSON-ready dict of everything the registry knows."""
        out: dict[str, Any] = {"t": time.time()}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Histogram):
                out[name] = m.as_dict()
            elif isinstance(m, Series):
                out[name] = list(m.values)
            else:
                out[name] = m.value
        for fn in self._collectors:
            try:
                out.update(fn())
            except Exception:  # a broken collector must not kill a dump
                out.setdefault("collector_errors", 0)
                out["collector_errors"] += 1
        return out


#: the process-wide default registry — the one the transport, workers,
#: masters, trainers, and the flight recorder all share
REGISTRY = Registry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str, bounds: tuple[float, ...] | None = None) -> Histogram:
    return REGISTRY.histogram(name, bounds)


def series(name: str, maxlen: int = 1024) -> Series:
    return REGISTRY.series(name, maxlen)
