"""Per-round data-plane buffers with threshold accounting.

Host-side equivalents of the reference's L1 layer (SURVEY.md §2-3):

- ``ScatteredDataBuffer`` — storage for incoming scatter chunks of *this worker's*
  block; counts contributions per chunk; answers "reached reduce threshold?";
  performs the sum reduction. In the reference this ``reduce`` is the JVM hot loop;
  here it is a vectorized accumulate (and the ICI path bypasses these buffers
  entirely — XLA's AllReduce is the reduction executor).
- ``ReducedDataBuffer`` — storage for reduced blocks received back from peers;
  tracks fill fraction vs ``th_complete``; exposes output + per-chunk counts for
  normalization.
- ``RoundBuffers`` — the bounded out-of-order round window the worker keeps so
  future-round messages are buffered rather than dropped (SURVEY.md §3
  ``AllreduceWorker`` "out-of-order round buffering").

These run the engine data path (unit tests, CPU fallback, DCN chunk movement); the
optional C++ accumulator in ``akka_allreduce_tpu/native`` accelerates ``store``'s
accumulate when built.
"""

from __future__ import annotations

import numpy as np

from akka_allreduce_tpu import native
from akka_allreduce_tpu.config import MetaDataConfig, ThresholdConfig


class RoundOutOfWindowError(Exception):
    """A message referenced a round outside the bounded out-of-order window —
    either already flushed (stale duplicate) or too far in the future."""


def _as_f32(value) -> np.ndarray:
    """View ``value`` as float32 without a copy whenever possible.

    Decoded wire payloads arrive as ``np.frombuffer`` views into the
    transport's receive buffer (control/remote.py) — viewing them again here
    must not materialize a defensive copy; the stores below copy exactly
    once, into their own accumulation/assembly storage. Raw buffers
    (memoryview/bytes) are accepted too, viewed in place."""
    if isinstance(value, np.ndarray):
        return value if value.dtype == np.float32 else value.astype(np.float32)
    if isinstance(value, (memoryview, bytes, bytearray)):
        return np.frombuffer(value, dtype=np.float32)
    return np.asarray(value, dtype=np.float32)


class ScatteredDataBuffer:
    """Accumulates scatter contributions for one worker's block in one round.

    The owner's block is partitioned into chunks of at most ``max_chunk_size``.
    Each peer (including the owner) sends one contribution per chunk; when a
    chunk's contribution count reaches ``ceil(th_reduce * peer_size)`` the chunk
    is ready to reduce. ``reduce`` returns the running sum and the contributor
    count (late contributions after the threshold still accumulate until reduce
    is called, matching the reference's "reduce at threshold, not at totality").
    """

    def __init__(
        self,
        metadata: MetaDataConfig,
        threshold: ThresholdConfig,
        peer_size: int,
        block_size: int | None = None,
    ) -> None:
        if peer_size <= 0:
            raise ValueError(f"peer_size must be positive, got {peer_size}")
        if block_size is not None and block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.metadata = metadata
        self.threshold = threshold
        self.peer_size = peer_size
        self.block_size = (
            metadata.block_size(peer_size) if block_size is None else block_size
        )
        self.num_chunks = max(
            1, -(-self.block_size // metadata.max_chunk_size)
        )  # ceil div
        # np.empty, not zeros: the first store per chunk copies instead of
        # accumulating, so the storage is never read uninitialized and the
        # page-touching zero pass is skipped (one full-buffer write per round
        # saved on the engine hot path)
        self._sums = np.empty(self.block_size, dtype=np.float32)
        self._counts = np.zeros(self.num_chunks, dtype=np.int32)
        self._contributed = np.zeros((self.num_chunks, peer_size), dtype=bool)
        self._reduced = np.zeros(self.num_chunks, dtype=bool)
        # the once-only crossing signal per chunk, tracked separately from
        # _reduced so the edge fires exactly once even when the caller
        # defers reduce() AND when set_reduce_trigger lowers the bar under
        # counts that already satisfy it
        self._edge_fired = np.zeros(self.num_chunks, dtype=bool)
        self.reduce_trigger = threshold.reduce_count(peer_size)

    def _chunk_bounds(self, chunk_id: int) -> tuple[int, int]:
        if not 0 <= chunk_id < self.num_chunks:
            raise IndexError(f"chunk_id {chunk_id} out of [0, {self.num_chunks})")
        start = chunk_id * self.metadata.max_chunk_size
        return start, min(start + self.metadata.max_chunk_size, self.block_size)

    def chunk_size(self, chunk_id: int) -> int:
        start, stop = self._chunk_bounds(chunk_id)
        return stop - start

    def store(self, value: np.ndarray, src_id: int, chunk_id: int) -> bool:
        """Accumulate one peer's contribution to one chunk (idempotent per src).

        Returns True iff this store just *crossed* the reduce trigger — the
        edge-triggered signal the worker uses to reduce-and-broadcast exactly
        once per chunk. Duplicate deliveries return False without accumulating.
        """
        start, stop = self._chunk_bounds(chunk_id)  # validates chunk_id
        if not 0 <= src_id < self.peer_size:
            raise IndexError(f"src_id {src_id} out of [0, {self.peer_size})")
        if self._contributed[chunk_id, src_id]:
            return False  # duplicate delivery — at-least-once transports are fine
        value = _as_f32(value)
        if value.shape != (stop - start,):
            raise ValueError(
                f"chunk {chunk_id} expects shape ({stop - start},), got {value.shape}"
            )
        # After reduce() the sum has been broadcast: late arrivals are counted
        # (observability) but no longer accumulated — nothing reads the sum
        # again, and skipping the add lets reduce() hand out a zero-copy view.
        if not self._reduced[chunk_id]:
            if self._counts[chunk_id] == 0:  # first contribution: plain copy
                np.copyto(self._sums[start:stop], value)
            else:
                native.accumulate(self._sums[start:stop], value)
        self._counts[chunk_id] += 1
        self._contributed[chunk_id, src_id] = True
        # >= guarded by the once-only edge flag (not ==): the trigger may
        # have been LOWERED by set_reduce_trigger under counts already
        # past it (a RoundPolicy arriving after peers ran ahead), and the
        # first store at or beyond the bar must still fire exactly once
        if (
            self._edge_fired[chunk_id]
            or self._reduced[chunk_id]
            or int(self._counts[chunk_id]) < self.reduce_trigger
        ):
            return False
        self._edge_fired[chunk_id] = True
        return True

    def set_reduce_trigger(self, trigger: int) -> list[int]:
        """Apply a per-round effective reduce trigger (RoundPolicy,
        control/adapt.py). Returns the chunks that ALREADY satisfy the new
        trigger and await reduce — the caller must reduce-and-broadcast
        them now, exactly as if ``store`` had just crossed: the edge signal
        cannot fire retroactively for contributions that predate the
        policy. Clamped to [1, peer_size]."""
        trigger = max(1, min(int(trigger), self.peer_size))
        if trigger == self.reduce_trigger:
            return []
        self.reduce_trigger = trigger
        ready = [
            c
            for c in range(self.num_chunks)
            if not self._reduced[c]
            and not self._edge_fired[c]
            and int(self._counts[c]) >= trigger
        ]
        for c in ready:
            self._edge_fired[c] = True
        return ready

    def count(self, chunk_id: int) -> int:
        self._chunk_bounds(chunk_id)
        return int(self._counts[chunk_id])

    def reach_reducing_threshold(self, chunk_id: int) -> bool:
        """Level query: chunk has enough contributions and awaits ``reduce``.

        Stays True from the trigger crossing until ``reduce`` is called; for the
        once-only broadcast decision use ``store``'s return value instead.
        """
        self._chunk_bounds(chunk_id)  # reject out-of-range (incl. negative) ids
        return (
            not self._reduced[chunk_id]
            and int(self._counts[chunk_id]) >= self.reduce_trigger
        )

    def reduce(self, chunk_id: int) -> tuple[np.ndarray, int]:
        """Return (summed chunk, contributor count) and mark the chunk reduced.

        The returned array is a read-only view into the buffer's storage —
        marking the chunk reduced freezes it (``store`` stops accumulating),
        so no copy is needed on the broadcast hot path.
        """
        start, stop = self._chunk_bounds(chunk_id)
        if int(self._counts[chunk_id]) == 0 and not self._reduced[chunk_id]:
            # no contributions: the storage was never written — present zeros
            self._sums[start:stop] = 0.0
        self._reduced[chunk_id] = True
        out = self._sums[start:stop]
        out.flags.writeable = False
        return out, int(self._counts[chunk_id])


class ReducedDataBuffer:
    """Assembles reduced blocks received back from peers into the round output.

    The full output buffer (size ``data_size``) is the concatenation of every
    peer's block. Each incoming ``ReduceBlock`` fills one chunk of one block and
    carries the contributor count for that chunk; completion fires when the
    number of filled chunks reaches ``ceil(th_complete * total_chunks)``.
    """

    def __init__(
        self,
        metadata: MetaDataConfig,
        threshold: ThresholdConfig,
        peer_size: int,
    ) -> None:
        if peer_size <= 0:
            raise ValueError(f"peer_size must be positive, got {peer_size}")
        self.metadata = metadata
        self.threshold = threshold
        self.peer_size = peer_size
        self.block_size = metadata.block_size(peer_size)
        self.chunks_per_block = metadata.chunks_per_block(peer_size)
        self.total_chunks = self.chunks_per_block * peer_size
        # Output covers peer_size * block_size >= data_size; trailing pad ignored.
        self._data = np.zeros(peer_size * self.block_size, dtype=np.float32)
        # Contributor counts are one integer per chunk (expanded to elements
        # lazily in get_with_counts) — per-element storage would add O(data)
        # host RAM per round buffer for nothing.
        self._chunk_counts = np.zeros(
            (peer_size, self.chunks_per_block), dtype=np.int32
        )
        self._filled = np.zeros((peer_size, self.chunks_per_block), dtype=bool)
        self.completion_trigger = threshold.complete_count(self.total_chunks)
        # chunk lengths within one block (same for every block): full chunks
        # then a possibly-short tail.
        self._chunk_lengths = np.array(
            [
                metadata.chunk_size(peer_size, c)
                for c in range(self.chunks_per_block)
            ],
            dtype=np.int64,
        )

    def _bounds(self, src_id: int, chunk_id: int) -> tuple[int, int]:
        if not 0 <= src_id < self.peer_size:
            raise IndexError(f"src_id {src_id} out of [0, {self.peer_size})")
        if not 0 <= chunk_id < self.chunks_per_block:
            raise IndexError(
                f"chunk_id {chunk_id} out of [0, {self.chunks_per_block})"
            )
        start = src_id * self.block_size + chunk_id * self.metadata.max_chunk_size
        stop = min(
            start + self.metadata.max_chunk_size, (src_id + 1) * self.block_size
        )
        return start, stop

    def store(
        self, value: np.ndarray, src_id: int, chunk_id: int, count: int
    ) -> None:
        """Place a reduced chunk from peer ``src_id`` into the output buffer."""
        start, stop = self._bounds(src_id, chunk_id)  # validates ids first
        if self._filled[src_id, chunk_id]:
            return  # duplicate delivery
        value = _as_f32(value)
        if value.shape != (stop - start,):
            raise ValueError(
                f"block {src_id} chunk {chunk_id} expects shape ({stop - start},),"
                f" got {value.shape}"
            )
        self._data[start:stop] = value
        self._chunk_counts[src_id, chunk_id] = count
        self._filled[src_id, chunk_id] = True

    @property
    def filled_chunks(self) -> int:
        return int(self._filled.sum())

    def reach_completion_threshold(self) -> bool:
        return self.filled_chunks >= self.completion_trigger

    def get_with_counts(self, copy: bool = True) -> tuple[np.ndarray, np.ndarray]:
        """(data, per-element contributor counts), trimmed to ``data_size``.

        Unfilled chunks read as zeros with count 0 — the consumer's divide
        leaves them untouched (partial completion is visible in the counts).

        ``copy=False`` returns a view into the buffer's storage — only for
        callers that immediately retire the buffer (the worker flushes and
        evicts the round in the same step); later ``store`` calls would write
        through the view.
        """
        n = self.metadata.data_size
        lengths = np.tile(self._chunk_lengths, self.peer_size)
        counts = native.expand_counts(self._chunk_counts.reshape(-1), lengths, n)
        data = self._data[:n]
        return (data.copy() if copy else data), counts


class RoundBuffers:
    """Bounded out-of-order window of per-round buffer pairs.

    The worker may receive ``ScatterBlock``/``ReduceBlock`` for rounds it has not
    started yet (peers run ahead within the line master's round window); those
    land in buffers created on demand. Rounds older than the completed horizon
    are dropped.
    """

    def __init__(
        self,
        metadata: MetaDataConfig,
        threshold: ThresholdConfig,
        peer_size: int,
        window: int = 4,
    ) -> None:
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        self.metadata = metadata
        self.threshold = threshold
        self.peer_size = peer_size
        self.window = window
        self._scattered: dict[int, ScatteredDataBuffer] = {}
        self._reduced: dict[int, ReducedDataBuffer] = {}
        self.completed_up_to = -1  # all rounds <= this are flushed

    def in_window(self, round_num: int) -> bool:
        return (
            self.completed_up_to
            < round_num
            <= self.completed_up_to + self.window
        )

    def _check_window(self, round_num: int) -> None:
        if not self.in_window(round_num):
            raise RoundOutOfWindowError(
                f"round {round_num} outside window "
                f"({self.completed_up_to}, {self.completed_up_to + self.window}]"
            )

    def scattered(self, round_num: int) -> ScatteredDataBuffer:
        self._check_window(round_num)
        if round_num not in self._scattered:
            self._scattered[round_num] = ScatteredDataBuffer(
                self.metadata, self.threshold, self.peer_size
            )
        return self._scattered[round_num]

    def reduced(self, round_num: int) -> ReducedDataBuffer:
        self._check_window(round_num)
        if round_num not in self._reduced:
            self._reduced[round_num] = ReducedDataBuffer(
                self.metadata, self.threshold, self.peer_size
            )
        return self._reduced[round_num]

    def complete(self, round_num: int) -> None:
        """Mark ``round_num`` flushed and evict everything at or below it."""
        self.completed_up_to = max(self.completed_up_to, round_num)
        for store in (self._scattered, self._reduced):
            for r in [r for r in store if r <= self.completed_up_to]:
                del store[r]

    def fast_forward(self, round_num: int) -> None:
        """Re-sync a lagging worker: abandon all rounds that can no longer fit
        in the window once ``round_num`` is admitted. Only call on
        master-authoritative evidence (a ``StartAllreduce``) that older rounds
        are already abandoned cluster-wide."""
        self.complete(round_num - self.window)
