"""The everything-on endurance run (VERDICT r4 #3).

Every feature is proven pairwise elsewhere; this module composes the
WHOLE framework in one unattended run — the flagship FSDP LM (remat /
prefetch / compressed collectives) under the elastic membership harness,
with async checkpointing, a mid-run restore, per-step metrics JSONL, and
at least one induced dropout + late-joiner re-mesh — and reports the
budgets that make up the recovery story: steady-state step time and MFU,
re-mesh latencies, checkpoint capture stalls, and the loss curve across
every disruption.

``python -m akka_allreduce_tpu soak`` runs it (flagship-sized by
default, on whatever devices are visible); tests/test_soak.py drives the
same loop at tiny shapes on the 8-device CPU mesh.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any

import numpy as np


@dataclasses.dataclass
class SoakReport:
    """Summary of one soak run (also serialized as the last JSONL line)."""

    steps: int
    wall_s: float
    steady_ms_per_step: float
    mfu: float | None
    first_loss: float
    final_loss: float
    remesh_events: list  # [{step, kind, seconds, n_devices}]
    # the re-mesh accounting SPLIT by provenance: `forced` re-meshes were
    # scripted by the harness itself (the leader-failover schedule entry —
    # membership unchanged, the cluster re-runs Prepare under a new
    # epoch), `detected` ones came out of the failure detector (drop /
    # rejoin edges). The old single trail conflated them, so a soak JSON
    # could not say whether churn was injected or observed.
    remeshes_forced: int
    remeshes_detected: int
    # {at_step, restored_step, seconds, source: disk|peer, [pull]} — the
    # disk-vs-peer A/B is readable from this one record: `seconds` always
    # measures the SAME span (wipe-if-any + state fetch + trainer restore),
    # and `source` names which path supplied the bytes
    restore: dict | None
    # peer replication bookkeeping when the replica sidecar is on
    # (chunks/bytes copied into the replica store across the run)
    replication: dict | None
    # the per-round policy trail of the AdaptiveController driven by the
    # chaos schedule's straggler evidence (``--chaos`` runs only):
    # {degrades, restores, final_level, mode_rounds: {mode: steps},
    # transitions: [...]} — so an A/B pair of soak JSONs can attribute a
    # throughput shift to mode changes instead of guessing
    adapt: dict | None
    checkpoint_saves: int
    # a skip because a background save is still in flight (real contention —
    # the stall signal) vs a skip because the step is already durable (the
    # post-restore rewind makes save() a dedup no-op; ADVICE r5 said the old
    # single counter conflated the two and inflated the stall metric)
    checkpoint_skipped_busy: int
    checkpoint_skipped_dedup: int
    max_capture_stall_s: float
    generation: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def run_soak(
    *,
    steps: int = 1000,
    nodes: int = 4,
    vocab: int = 256,
    d_model: int = 2048,
    n_heads: int | None = None,
    n_layers: int = 8,
    seq_len: int = 2048,
    batch_per_replica: int = 2,
    bf16: bool = True,
    remat: str | bool = "params",
    prefetch: bool = True,
    compress: str | None = "int8",
    learning_rate: float = 1e-3,
    drop_at: int | None = None,
    rejoin_at: int | None = None,
    restore_at: int | None = None,
    chaos_seed: int | None = None,
    checkpoint_every: int = 100,
    checkpoint_dir: str | None = None,
    delta: bool = False,
    peer_restore: bool = False,
    metrics_out: str | None = None,
    log=print,
) -> SoakReport:
    """Run the composed soak loop; every disruption is induced from
    inside (no manual intervention). Defaults follow the round-4 flagship
    recipe (``--remat params --prefetch --compress int8``); the drop /
    rejoin / restore steps default to 1/4, 1/2 and 3/4 of the run.

    ``chaos_seed`` (``soak --chaos SEED``) swaps the single scripted
    drop/rejoin for a deterministic seeded schedule of per-node silence
    windows (``control.chaos.membership_schedule``): each node other than
    0 independently flaps in and out, so one run exercises MANY detector
    trips and re-meshes — and the same seed replays the same churn.

    ``peer_restore`` (requires ``delta``) drives the mid-run restore
    through the peer state-transfer path instead of the local disk
    (RESILIENCE.md "Recovery"): every completed delta save is replicated
    into a replica ``ChunkStore`` sidecar, and at ``restore_at`` the local
    delta store is WIPED (the disk-loss scenario) and rebuilt chunk by
    chunk from the replica through the same verify-before-publish gate the
    TCP pull uses — the report's ``restore.source`` flips to ``"peer"``
    and ``restore.seconds`` measures the full wipe+pull+restore span, so
    the disk-vs-peer A/B is one flag and one JSON field apart."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.train import (
        AsyncDeltaCheckpointer,
        AsyncTrainerCheckpointer,
        ElasticTrainer,
        FSDPLMTrainer,
    )
    from akka_allreduce_tpu.utils import metrics as metrics_mod
    from akka_allreduce_tpu.utils.benchmarking import (
        mfu as mfu_of,
        transformer_train_flops,
    )

    drop_at = steps // 4 if drop_at is None else drop_at
    rejoin_at = steps // 2 if rejoin_at is None else rejoin_at
    restore_at = (3 * steps) // 4 if restore_at is None else restore_at
    n_heads = n_heads or max(1, d_model // 128)

    devices = jax.devices()
    nodes = min(nodes, max(2, len(devices)))
    per = max(1, len(devices) // nodes)
    if len(devices) >= nodes:
        assignment = {
            k: devices[k * per : (k + 1) * per] for k in range(nodes)
        }
    else:
        # one real chip: a zero-device control node still exercises the
        # full membership/re-mesh machinery (bench-suite config 5's shape)
        assignment = {0: list(devices), 1: []}
        nodes = 2
    lost = nodes - 1
    now = {"t": 0.0}

    def factory(mesh):
        return FSDPLMTrainer(
            mesh,
            vocab=vocab,
            d_model=d_model,
            n_heads=n_heads,
            n_layers=n_layers,
            seq_len=seq_len,
            learning_rate=learning_rate,
            compute_dtype=jnp.bfloat16 if bf16 else jnp.float32,
            remat=remat,
            prefetch=prefetch,
            compress=compress,
        )

    silent_plan = None
    leader_kill = None
    adapt_ctl = None
    adapt_lags: dict[int, int] = {}
    # steps the simulated control plane is LEADERLESS after the kill (the
    # lease window): the detector dies with the leader — no polls, no
    # expulsions — then the standby's takeover re-meshes everyone
    failover_steps = 3
    if chaos_seed is not None:
        from akka_allreduce_tpu.config import AdaptConfig
        from akka_allreduce_tpu.control.adapt import AdaptiveController
        from akka_allreduce_tpu.config import ThresholdConfig
        from akka_allreduce_tpu.control.chaos import (
            leader_kill_step,
            membership_schedule,
        )

        silent_plan = membership_schedule(chaos_seed, nodes, steps)
        leader_kill = leader_kill_step(chaos_seed, steps)
        # the adaptive controller rides the SAME seeded schedule: a node's
        # consecutive silent steps feed it as contribution lag, so the
        # policy trail is a pure function of the chaos seed (deterministic
        # A/B). The trail is REPORTED, not applied — re-compiling the
        # trainer per mode flip would swamp the soak's timing story; the
        # TCP cluster (cluster-master --adapt) is where the policy drives
        # the actual wire.
        adapt_ctl = AdaptiveController(
            AdaptConfig(
                enabled=True, window=4, min_dwell=8,
                lag_degrade=3, lag_restore=1,
            ),
            ThresholdConfig(),
        )
    elastic = ElasticTrainer(factory, assignment, clock=lambda: now["t"])
    churn = (
        f"chaos seed {chaos_seed} "
        f"({sum(len(v) for v in silent_plan.values())} node-step silences, "
        f"leader kill@{leader_kill})"
        if silent_plan is not None
        else f"drop@{drop_at} rejoin@{rejoin_at}"
    )
    log(
        f"soak: {elastic.trainer.param_count / 1e6:.1f}M params over "
        f"{elastic.trainer.n_devices} devices / {nodes} nodes; "
        f"{churn} restore@{restore_at}"
    )

    ckpt_dir = checkpoint_dir or tempfile.mkdtemp(prefix="soak_ckpt_")
    if peer_restore and not delta:
        raise ValueError(
            "peer_restore replicates delta-checkpoint chunks; pass delta=True"
        )
    ckpt_cls = AsyncDeltaCheckpointer if delta else AsyncTrainerCheckpointer
    ckpt = ckpt_cls(ckpt_dir)
    replica = None
    replication: dict | None = None
    if peer_restore:
        from akka_allreduce_tpu.control.statetransfer import ChunkStore

        # the replica sidecar: the in-process stand-in for the K=2 peer
        # stores the TCP cluster pushes to — same layout, same
        # verify-before-publish copy path (copy_delta)
        replica = ChunkStore(ckpt_dir + "_replica")
        replication = {"rounds": 0, "chunks_copied": 0, "bytes_copied": 0}
    ds = data.lm_copy_task(seq_len, vocab=vocab)
    logger = (
        metrics_mod.MetricsLogger(metrics_out) if metrics_out else None
    )

    step_ms: list[float] = []
    losses: list[float] = []
    restore_rec: dict | None = None
    # run-scoped metrics registry (obs.metrics): the loop records its
    # checkpoint / re-mesh bookkeeping HERE and the final SoakReport reads
    # it BACK, so the report and any live metrics consumer (log_snapshot
    # below) can never disagree — there is one set of numbers.
    from akka_allreduce_tpu.obs.metrics import Registry

    reg = Registry()
    remesh_events = reg.series("soak.remesh_events")
    c_steps = reg.counter("soak.steps")
    c_saves = reg.counter("soak.checkpoint.saves")
    c_skip_busy = reg.counter("soak.checkpoint.skipped_busy")
    c_skip_dedup = reg.counter("soak.checkpoint.skipped_dedup")
    g_capture = reg.gauge("soak.checkpoint.max_capture_stall_s")
    g_loss = reg.gauge("soak.loss")
    # restore accounting (RESILIENCE.md "Recovery"): the source split and
    # the seconds live in the SAME registry the report reads, so the soak
    # JSON and any live metrics consumer agree by construction
    c_restore_disk = reg.counter("soak.restore.from_disk")
    c_restore_peer = reg.counter("soak.restore.from_peer")
    g_restore_s = reg.gauge("soak.restore.seconds")
    replicated = {"step": -1}

    def replicate_completed() -> None:
        """Mirror the newest COMPLETED delta save into the replica store
        (content-addressed: an unchanged leaf copies zero bytes)."""
        if replica is None or ckpt.busy():
            return
        latest = ckpt.latest_step()
        if latest is None or latest <= replicated["step"]:
            return
        from akka_allreduce_tpu.control.statetransfer import ChunkStore, copy_delta

        s = copy_delta(ChunkStore(ckpt_dir), replica, step=latest)
        replicated["step"] = latest
        replication["rounds"] += 1
        replication["chunks_copied"] += s["chunks_copied"]
        replication["bytes_copied"] += s["bytes_copied"]
    compile_steps: set[int] = {0}  # steps whose time includes an XLA compile
    t_start = time.perf_counter()

    def batch(seed):
        rows = elastic.trainer.dp * batch_per_replica
        return next(ds.batches(rows, 1, seed_offset=seed))

    adapt_trail = reg.series("soak.adapt.transitions")
    adapt_mode_steps: dict[str, int] = {}
    for step in range(steps):
        if silent_plan is not None:
            silent = silent_plan.get(step, frozenset())
            alive = [k for k in range(nodes) if k not in silent]
        else:
            alive = [
                k for k in range(nodes)
                if not (drop_at <= step < rejoin_at and k == lost)
            ]
        for k in alive:
            elastic.heartbeat(k)
        # steady 1 s heartbeat cadence: the detector's interval model
        # settles in the first few steps, and a node that then goes
        # silent accrues phi within a handful of ticks
        now["t"] += 1.0
        t0 = time.perf_counter()
        members_before = len(elastic.member_nodes)
        forced_kind = None
        if (
            leader_kill is not None
            and leader_kill <= step < leader_kill + failover_steps
        ):
            # leaderless window: the failure detector died WITH the leader,
            # so nobody polls and nobody is expelled (the warm standby
            # carries the membership state — nothing is forgotten)
            remeshed = False
        elif leader_kill is not None and step == leader_kill + failover_steps:
            # the standby's lease expired and it took over: every node
            # re-joins the new leader -> one full re-mesh with unchanged
            # membership (the in-process analog of the TCP failover walk)
            remeshed = elastic.remesh("leader_failover")
            forced_kind = "leader_failover"
        else:
            remeshed = elastic.poll()
        x, y = batch(step)
        m = elastic.train_step(x, y)
        dt = time.perf_counter() - t0
        if remeshed:
            # kind from the authoritative membership delta, not the step
            # index (phi detection lags the induced silence by a few
            # heartbeats)
            kind = forced_kind or (
                "drop"
                if len(elastic.member_nodes) < members_before
                else "rejoin"
            )
            remesh_events.append(
                {
                    "step": step,
                    "kind": kind,
                    "seconds": round(dt, 3),
                    "n_devices": elastic.trainer.n_devices,
                }
            )
            reg.counter(f"soak.remesh.{kind}").inc()
            # provenance split (pinned in test_soak): forced = the
            # harness scripted it; detected = the phi detector found it
            reg.counter(
                "soak.remesh.forced"
                if forced_kind
                else "soak.remesh.detected"
            ).inc()
            compile_steps.add(step)
            log(
                f"step {step}: re-mesh ({kind}) -> "
                f"{elastic.trainer.n_devices} devices in {dt:.2f}s"
            )
        if adapt_ctl is not None:
            # one "round" of straggler evidence per step: a silent node's
            # lag is its consecutive silent steps (round units — the same
            # shape the TCP master feeds from LineMaster.worker_lags)
            for k in range(nodes):
                adapt_lags[k] = 0 if k in alive else adapt_lags.get(k, 0) + 1
            pol = adapt_ctl.observe_round(step, dict(adapt_lags), {})
            if pol is not None:
                rec = dict(adapt_ctl.decisions[-1], step=step)
                adapt_trail.append(rec)
                reg.counter(
                    "soak.adapt.degrades"
                    if rec["to"] > rec["from"]
                    else "soak.adapt.restores"
                ).inc()
                log(
                    f"step {step}: adapt level {rec['from']} -> "
                    f"{rec['to']} ({'+'.join(rec['why'])}) policy "
                    f"{rec['policy']}"
                )
            mode = adapt_ctl.policy().wire or "full"
            adapt_mode_steps[mode] = adapt_mode_steps.get(mode, 0) + 1
        step_ms.append(dt * 1e3)
        losses.append(m.loss)
        c_steps.inc()
        g_loss.set(m.loss)
        if logger:
            logger.log_event(
                step=step, loss=m.loss, ms=round(dt * 1e3, 2)
            )

        if step == restore_at and ckpt.latest_step() is not None:
            t0 = time.perf_counter()
            ckpt.wait_until_finished()
            source, pull = "disk", None
            if replica is not None:
                # the disk-loss drill: catch the replica up, WIPE the local
                # delta store, rebuild it chunk-verified from the replica —
                # then restore through the ordinary checkpointer path so
                # the restored state is byte-identical to the disk path
                import shutil

                from akka_allreduce_tpu.control.statetransfer import (
                    ChunkStore,
                    copy_delta,
                )

                replicate_completed()
                own = ChunkStore(ckpt_dir)
                shutil.rmtree(own.blobs)
                for m in own.manifests().values():
                    m.unlink()
                own.blobs.mkdir()
                pull = copy_delta(replica, own, verify=True)
                source = "peer"
            restored = ckpt.restore(elastic.trainer)
            rs = time.perf_counter() - t0
            restore_rec = {
                "at_step": step,
                "restored_step": int(restored),
                "seconds": round(rs, 3),
                "source": source,
            }
            if pull is not None:
                restore_rec["pull"] = pull
            (c_restore_peer if source == "peer" else c_restore_disk).inc()
            g_restore_s.set(restore_rec["seconds"])
            compile_steps.add(step + 1)  # rewound shapes may recompile
            log(
                f"step {step}: restored checkpoint of step {restored} "
                f"from {source} in {rs:.2f}s; training continues from there"
            )

        replicate_completed()
        if checkpoint_every and step and step % checkpoint_every == 0:
            if ckpt.busy():
                # a background save is still in flight: THIS is the
                # contention the stall metric exists to count
                c_skip_busy.inc()
            else:
                t0 = time.perf_counter()
                launched = ckpt.save(elastic.trainer)
                cap = time.perf_counter() - t0
                if launched:
                    c_saves.inc()
                    g_capture.set(max(g_capture.value, cap))
                else:
                    # not busy and not launched: the step is already durable
                    # (e.g. the restore rewound step_num onto a saved step)
                    c_skip_dedup.inc()

    ckpt.wait_until_finished()
    wall = time.perf_counter() - t_start
    steady = [
        ms for i, ms in enumerate(step_ms) if i not in compile_steps
    ]
    steady_ms = statistics.median(steady) if steady else float("nan")
    flops = transformer_train_flops(
        n_params=elastic.trainer.param_count,
        batch=elastic.trainer.dp * batch_per_replica,
        seq=seq_len,
        d_model=d_model,
        n_layers=n_layers,
    )
    # the report is a READ of the registry — same numbers any live
    # metrics_snapshot consumer saw, by construction
    report = SoakReport(
        steps=steps,
        wall_s=round(wall, 1),
        steady_ms_per_step=round(steady_ms, 1),
        # flops is the GLOBAL whole-batch work -> whole-mesh peak
        mfu=mfu_of(
            flops, steady_ms / 1e3, n_devices=elastic.trainer.n_devices
        ),
        first_loss=round(losses[0], 4),
        final_loss=round(losses[-1], 4),
        remesh_events=list(remesh_events.values),
        remeshes_forced=reg.counter("soak.remesh.forced").value,
        remeshes_detected=reg.counter("soak.remesh.detected").value,
        restore=restore_rec,
        replication=replication,
        adapt=(
            {
                "degrades": reg.counter("soak.adapt.degrades").value,
                "restores": reg.counter("soak.adapt.restores").value,
                "final_level": adapt_ctl.level,
                "mode_rounds": dict(adapt_mode_steps),
                "transitions": list(adapt_trail.values),
            }
            if adapt_ctl is not None
            else None
        ),
        checkpoint_saves=c_saves.value,
        checkpoint_skipped_busy=c_skip_busy.value,
        checkpoint_skipped_dedup=c_skip_dedup.value,
        max_capture_stall_s=round(g_capture.value, 3),
        generation=elastic.generation,
    )
    if logger:
        logger.log_snapshot(reg)
        logger.log_event(summary=report.as_dict())
        logger.close()
    return report
