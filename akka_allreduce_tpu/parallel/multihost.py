"""Multi-host (multi-slice / pod) bootstrap glue.

The reference scales across JVMs with Akka Cluster over the host network
(SURVEY.md §3 "Distributed communication backend"); the TPU-native equivalent
splits by traffic class: *payloads* ride ICI within a slice and DCN across
slices via XLA collectives — exactly the same ``psum``/``shard_map`` code as
single-host, just over a global mesh — while *control* (membership, round
scheduling, elasticity) stays on the host network (control/bootstrap.py, or
``jax.distributed``'s coordination service bootstrapped here).

Division of labor with the rest of the framework:

- this module: process-group init (``jax.distributed``) + global mesh
  construction + host-local <-> global array plumbing;
- ``comm/``: the collectives themselves — unchanged, they take a Mesh;
- ``control/``: threshold rounds + elastic membership — unchanged, its
  transport already crosses hosts.

On a TPU pod each process (host) owns 4-8 local chips; after
:func:`initialize` every process sees the global device list and builds the
SAME mesh, and jitted SPMD programs launch collectively. There is no
multi-host hardware in CI, so these helpers are exercised there only for
their single-process degenerate forms; the multi-chip sharding itself is
validated by ``__graft_entry__.dryrun_multichip`` on the virtual mesh.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.parallel.mesh import LINE_AXIS, grid_factors


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the JAX process group (idempotent for single-process runs).

    With no arguments, defers to ``jax.distributed``'s auto-detection (TPU
    pod metadata, or the ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
    / ``JAX_PROCESS_ID`` environment, matching the reference's seed-node
    configuration in ``application.conf``). Single-process runs (everything
    in CI here) skip initialization entirely.
    """
    env = os.environ
    coordinator_address = coordinator_address or env.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and env.get("JAX_NUM_PROCESSES"):
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and env.get("JAX_PROCESS_ID"):
        process_id = int(env["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        return  # single process: nothing to join
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_line_mesh(axis: str = LINE_AXIS) -> Mesh:
    """1D mesh over every chip of every process (pod-wide allreduce line)."""
    return jax.make_mesh((len(jax.devices()),), (axis,))


def slice_grid_mesh(axes: tuple[str, str] = ("rows", "cols")) -> Mesh:
    """2D butterfly grid over the global device list, laid out so the
    ``cols`` reduction stage stays entirely within one process/slice (rides
    ICI) while the ``rows`` stage crosses hosts (rides DCN) — SURVEY.md §4.3
    scaled up.

    ``jax.devices()`` orders devices process-contiguously, so shaping the
    grid as ``(n_processes, chips_per_process)`` puts each grid row inside
    one process: a psum over ``cols`` (fixed row, varying col) never leaves
    the host, and a psum over ``rows`` is the cross-host stage.
    """
    devs = jax.devices()
    n_local = max(1, len(jax.local_devices()))
    n = len(devs)
    if n % n_local == 0 and n // n_local > 1:
        rows, cols = n // n_local, n_local
    else:
        rows, cols = grid_factors(n)
    grid = np.array(devs).reshape(rows, cols)
    return Mesh(grid, axes)


def host_local_to_global(
    x: np.ndarray, mesh: Mesh, spec: P
) -> jax.Array:
    """Assemble per-process host arrays into one global sharded array.

    Each process passes ITS shard (the reference's per-worker payload); the
    result is the global array the collectives consume. Single-process: a
    plain ``device_put``.
    """
    if jax.process_count() == 1:
        return jax.device_put(x, NamedSharding(mesh, spec))
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(x, mesh, spec)


def process_allgather(x) -> np.ndarray:
    """Gather a small host value from every process (control-plane sync
    helper, e.g. agreeing on a contributor mask before a round)."""
    if jax.process_count() == 1:
        return np.asarray(x)[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x))
