"""Device-mesh construction.

Topology roles (mapping the reference's line/grid organization, SURVEY.md §4.3):

- ``line_mesh(n)``   — one line of n workers; allreduce rides the ``line`` axis.
- ``grid_mesh(r, c)``— the 2D butterfly grid; a round reduces along ``rows``
  then ``cols`` (Kylix-style two-stage scatter-reduce).

On real hardware ``jax.make_mesh`` lays devices out so neighboring mesh
coordinates are ICI neighbors; on the CPU test backend any layout works.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
from jax.sharding import Mesh

LINE_AXIS = "line"
GRID_AXES = ("rows", "cols")
DATA_SEQ_AXES = ("data", "seq")


def _resolve_devices(
    num_devices: int | None, devices: Sequence[jax.Device] | None
) -> list[jax.Device]:
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return devices


def line_mesh(
    num_devices: int | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis: str = LINE_AXIS,
) -> Mesh:
    """A 1D mesh: one line of workers."""
    devs = _resolve_devices(num_devices, devices)
    return jax.make_mesh((len(devs),), (axis,), devices=devs)


def grid_factors(n: int) -> tuple[int, int]:
    """Split n into the most-square (rows, cols) factorization, rows <= cols."""
    if n <= 0:
        raise ValueError(f"need a positive device count, got {n}")
    best = (1, n)
    for r in range(1, int(math.isqrt(n)) + 1):
        if n % r == 0:
            best = (r, n // r)
    return best


def grid_mesh(
    rows: int | None = None,
    cols: int | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    axes: tuple[str, str] = GRID_AXES,
) -> Mesh:
    """A 2D butterfly grid mesh. With no shape given, factors the device count
    into the most-square grid (16 devices -> 4x4, matching BASELINE.json:8's
    16-worker butterfly)."""
    devs = _resolve_devices(
        rows * cols if rows is not None and cols is not None else None, devices
    )
    n = len(devs)
    if rows is None and cols is None:
        rows, cols = grid_factors(n)
        devs = devs[: rows * cols]
    elif rows is None or cols is None:
        # honor the given dimension; derive the other from the device count
        given = rows if rows is not None else cols
        if n % given == 0:
            derived = n // given
        else:
            raise ValueError(
                f"{n} devices do not divide into a grid with one side {given}"
            )
        rows, cols = (given, derived) if rows is not None else (derived, given)
    if rows * cols != n:
        raise ValueError(f"grid {rows}x{cols} != {n} devices")
    return jax.make_mesh((rows, cols), axes, devices=devs)


def data_seq_mesh(
    dp: int | None = None,
    sp: int | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    axes: tuple[str, str] = DATA_SEQ_AXES,
) -> Mesh:
    """A 2D (data, seq) mesh for long-context training: DP replicas along
    ``data``, each replica's sequence sharded along ``seq`` (ring attention /
    Ulysses ride the ``seq`` axis — ops/ring_attention.py). With no shape
    given, prefers the most-square factorization with ``sp`` the larger side
    (sequence parallelism is the scarcer resource). Same shape logic as
    :func:`grid_mesh`, only the axis roles differ."""
    return grid_mesh(dp, sp, devices=devices, axes=axes)


DATA_SEQ_MODEL_AXES = ("data", "seq", "model")


def data_seq_model_mesh(
    dp: int,
    sp: int,
    tp: int,
    *,
    devices: Sequence[jax.Device] | None = None,
    axes: tuple[str, str, str] = DATA_SEQ_MODEL_AXES,
) -> Mesh:
    """A 3D (data, seq, model) mesh: DP replicas x sequence shards x
    Megatron-style tensor-parallel groups. ``model`` is the innermost axis —
    TP's per-layer psums are the most latency-sensitive collectives, so its
    groups should map to directly-wired neighbor chips."""
    devs = _resolve_devices(dp * sp * tp, devices)
    return jax.make_mesh((dp, sp, tp), axes, devices=devs)
