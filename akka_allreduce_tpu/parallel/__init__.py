"""Mesh and sharding helpers (the TPU-build's topology layer).

The reference organizes workers into lines and 2D grids over Akka Cluster
membership (SURVEY.md §3 "Node / dimension actors"); here topology is a
``jax.sharding.Mesh`` whose axes play the same roles: a 1D ``line`` mesh is one
line of workers, a 2D ``rows``×``cols`` mesh is the butterfly grid.
"""

from akka_allreduce_tpu.parallel.mesh import (  # noqa: F401
    DATA_SEQ_AXES,
    DATA_SEQ_MODEL_AXES,
    LINE_AXIS,
    GRID_AXES,
    data_seq_mesh,
    data_seq_model_mesh,
    grid_factors,
    grid_mesh,
    line_mesh,
)
from akka_allreduce_tpu.parallel.multihost import (  # noqa: F401
    global_line_mesh,
    host_local_to_global,
    process_allgather,
    slice_grid_mesh,
)
from akka_allreduce_tpu.parallel.multihost import (  # noqa: F401
    initialize as initialize_multihost,
)
