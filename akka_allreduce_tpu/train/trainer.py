"""DP trainer: per-step threshold-masked gradient allreduce inside one jitted
SPMD step (the pure-TPU form of the reference's grad-sync configs,
BASELINE.json:9-10 / SURVEY.md §4.4).

Design: batch sharded over the mesh's data axes, params/optimizer state
replicated; forward + backward run per device; the gradient pytree is
flattened and goes through ONE fused masked psum (optionally bucketed at
``max_chunk_size`` granularity — the reference's chunked buffer); the
optimizer applies the partial-average gradient. Invalid devices (mask 0) still
compute — XLA collectives are all-or-nothing — but contribute nothing, exactly
the threshold-contribution semantics of SURVEY.md §8.1 step 3.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.binder.api import flatten_pytree
from akka_allreduce_tpu.comm.allreduce import (
    backward_psum_sync,
    backward_ring_sync,
    backward_sync_ef,
    expand_counts,
    masked_psum,
    ring_allreduce_sum,
    ring_ef_residual,
)


@dataclasses.dataclass
class TrainStepMetrics:
    step: int
    loss: float
    contributors: float


def ef_fold(flat: jax.Array, ef) -> jax.Array:
    """Fold the EF residual into this step's contribution: ``c = g + e``."""
    return flat if ef is None else flat + ef.reshape(-1)


def ef_residual(
    c: jax.Array,
    v: jax.Array,
    ef,
    *,
    compress: str = "bf16",
) -> jax.Array:
    """``e' = c - sent``; all of ``c`` carries forward when the device was
    masked out.

    ``compress="bf16"``: ``sent`` mirrors masked_psum's mask-then-cast
    EXACTLY (what the bf16 collective actually summed from this device).
    The bf16 cast error is entirely local, so this residual is the
    complete compensation. The int8 ring no longer routes through here:
    its residual comes from the ring itself
    (``ring_allreduce_sum(..., return_residual=True)`` — per-hop
    accounting including partial-sum requantization, VERDICT r4 #4c).
    """
    if compress != "bf16":
        raise ValueError(
            f"ef_residual is the bf16 mask-then-cast mirror; int8 uses the "
            f"ring's per-hop residual (got compress={compress!r})"
        )
    m = c * v
    sent = m.astype(jnp.bfloat16).astype(jnp.float32)
    return (c - sent).reshape(ef.shape)


def default_classification_loss():
    """Mean softmax cross-entropy over integer labels (the trainers' default)."""
    return lambda logits, y: optax.softmax_cross_entropy_with_integer_labels(
        logits, y
    ).mean()


def normalize_valid(valid: Sequence[float] | None, n: int) -> np.ndarray:
    """Contributor mask -> validated (n,) float32 array (shared by trainers)."""
    if valid is None:
        return np.ones((n,), np.float32)
    arr = np.asarray(valid, np.float32)
    if arr.shape != (n,):
        raise ValueError(f"valid must have shape ({n},), got {arr.shape}")
    return arr


def run_chain_cached(
    trainer,
    sampler,
    steps: int,
    rows: int,
    build,
    valid: Sequence[float] | None,
    n_valid: int,
    valid_sharding,
    seed: int,
    fetch_metrics: bool = True,
    extra_state: tuple = (),
) -> tuple:
    """Shared ``train_chain`` scaffolding for every trainer.

    - chain cache keyed on the shape config ``(steps, rows)`` with the
      sampler object pinned by IDENTITY in the entry: ``id()`` alone could
      match a new sampler allocated at a recycled address after the old one
      was garbage-collected, silently reusing a chain compiled against the
      old closure;
    - contributor mask normalized to ``(n_valid,)`` and placed;
    - the PRNG key folds in ``step_num`` so consecutive chain calls continue
      the data stream instead of replaying the same batches.

    The built chain must have signature ``(params, opt_state, *extras, key,
    valid) -> (params, opt_state, *new_extras, *metric_arrays)``, where
    ``extra_state`` names the trainer attributes holding the extras (e.g.
    ``("_ef",)`` for the error-feedback residual); new state is swapped into
    the trainer here and the stacked metric arrays are returned as host numpy.
    """
    cache_key = (steps, rows)
    entry = trainer._chains.get(cache_key)
    if entry is None or entry[0] is not sampler:
        trainer._chains[cache_key] = (sampler, build())
    vd = jax.device_put(normalize_valid(valid, n_valid), valid_sharding)
    key = jax.device_put(
        jax.random.fold_in(jax.random.PRNGKey(seed), trainer.step_num),
        trainer._replicated,
    )
    extras = tuple(getattr(trainer, name) for name in extra_state)
    out = trainer._chains[cache_key][1](
        trainer.params, trainer.opt_state, *extras, key, vd
    )
    trainer.params, trainer.opt_state = out[0], out[1]
    for i, name in enumerate(extra_state):
        setattr(trainer, name, out[2 + i])
    out = out[:2] + out[2 + len(extra_state):]
    if not fetch_metrics:
        # raw device arrays: benchmarks time the chain without the O(steps)
        # metric fetch (the device_get payload grows linearly with steps and
        # would ride on the timing slope instead of cancelling)
        return out[2:]
    return tuple(np.asarray(jax.device_get(o)) for o in out[2:])


def place_batch(x, y, n_devices: int, data_sharding):
    """Validate divisibility and place an (x, y) batch on the mesh.

    Single-process: ``x``/``y`` are the GLOBAL batch. Multiprocess (pod
    runtime — the sharding's mesh spans OS processes): each process passes
    its HOST-LOCAL rows and they assemble into one global sharded batch via
    ``parallel.multihost.host_local_to_global`` — the pod form of the
    reference's per-worker dataSource pull (SURVEY.md §4.4). train_step,
    accuracy, and ``train_step_accum`` (which builds its
    (devices·accum, micro, ...) layout host-locally per process) all ride
    this seam.
    """
    if not data_sharding.is_fully_addressable:
        # the mesh spans OS processes (a fully-local mesh — e.g. a
        # single-device oracle inside a pod run — takes the plain path)
        from akka_allreduce_tpu.parallel import multihost

        mesh, spec = data_sharding.mesh, data_sharding.spec
        pid = jax.process_index()
        local_share = sum(
            1 for d in mesh.devices.flat if d.process_index == pid
        )
        if local_share == 0 or x.shape[0] % local_share:
            raise ValueError(
                f"host-local batch {x.shape[0]} not divisible by this "
                f"process's {local_share} mesh devices"
            )
        return (
            multihost.host_local_to_global(
                np.asarray(x, np.float32), mesh, spec
            ),
            multihost.host_local_to_global(
                np.asarray(y, np.int32), mesh, spec
            ),
        )
    if x.shape[0] % n_devices:
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by {n_devices}"
        )
    x = jax.device_put(np.asarray(x, np.float32), data_sharding)
    y = jax.device_put(np.asarray(y, np.int32), data_sharding)
    return x, y


def place_tokens(x, y, data_sharding, *, seq_len: int, dp: int):
    """Token-LM twin of :func:`place_batch` (both arrays int32, batch rows
    over the data axis, the seq dim over any seq axis in the spec).

    Single-process: ``x``/``y`` are the GLOBAL (batch, seq_len) arrays.
    Pod runtime (the sharding's mesh spans OS processes): each process
    passes the HOST-LOCAL slice matching its devices' block of the
    sharding — for the (data, seq) layouts used here, its DP rows' full
    sequences when its devices cover whole replica rows.
    """
    if x.shape[1] != seq_len:
        raise ValueError(f"sequence length {x.shape[1]} != {seq_len}")
    if not data_sharding.is_fully_addressable:
        from akka_allreduce_tpu.parallel import multihost

        mesh, spec = data_sharding.mesh, data_sharding.spec
        return (
            multihost.host_local_to_global(
                np.asarray(x, np.int32), mesh, spec
            ),
            multihost.host_local_to_global(
                np.asarray(y, np.int32), mesh, spec
            ),
        )
    if x.shape[0] % dp:
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by dp={dp}"
        )
    return (
        jax.device_put(np.asarray(x, np.int32), data_sharding),
        jax.device_put(np.asarray(y, np.int32), data_sharding),
    )


def place_mask(valid_arr: np.ndarray, data_sharding):
    """Place the GLOBAL per-device contributor mask on the mesh.

    The mask is control-plane state every process agrees on (the membership
    view), so callers always pass the full (n_devices,) array; on a pod
    each process extracts the rows its local devices own before the
    host-local -> global assembly.
    """
    if data_sharding.is_fully_addressable:
        return jax.device_put(valid_arr, data_sharding)
    from akka_allreduce_tpu.parallel import multihost

    arr = np.asarray(valid_arr)
    # the sharding's own index map says which mask ROWS this process's
    # devices hold (NOT one entry per device: on a multi-axis mesh several
    # devices share a data row, and the mask length is the data extent)
    pid = jax.process_index()
    imap = data_sharding.devices_indices_map(arr.shape)
    starts = [
        idx[0].start or 0
        for d, idx in imap.items()
        if d.process_index == pid
    ]
    stops = [
        idx[0].stop if idx[0].stop is not None else arr.shape[0]
        for d, idx in imap.items()
        if d.process_index == pid
    ]
    if not starts:
        # a clean error beats min()-of-empty followed by peers hanging in
        # the collective (same contract as place_batch's 0-device message)
        raise ValueError(
            "this process owns no devices in the training mesh; a "
            "zero-device participant cannot feed the pod collective"
        )
    return multihost.host_local_to_global(
        arr[min(starts) : max(stops)], data_sharding.mesh, data_sharding.spec
    )


class DPTrainer:
    """Data-parallel trainer over every axis of ``mesh``.

    Args:
      model: a flax module with ``init``/``apply``.
      mesh: device mesh; the batch is sharded across ALL its axes jointly
        (a 2D mesh gives the butterfly-grid layout of BASELINE.json:8).
      example_input: one device's worth of input used for ``init``.
      optimizer: optax transform (default: SGD).
      bucket_size: gradient bucket size in elements (None = single fused psum).
      compress: None | "bf16" | "int8" — gradient wire compression. bf16
        runs the psum collective at half width; int8 rides the explicit
        ring schedule with per-segment max-abs scales at a quarter (one
        mesh axis only; the ring segments by device count, so
        ``bucket_size`` does not set its wire chunking). Counts and the
        optimizer state stay float32 either way. Forces the
        explicit-collective path (one bucket when ``bucket_size`` is None).
      error_feedback: carry each device's quantization residual into its
        next contribution (EF-SGD): ``c = g + e; send cast(c·v);
        e' = c − sent`` — what compression withholds this step is re-sent
        the next, making the lossy sync unbiased over time. A masked-out
        device (v=0) sends nothing, so its ENTIRE contribution carries
        forward — threshold dropout loses no gradient signal, only delays
        it. Requires ``compress``. Works on train_step, train_step_accum
        (residual of the accumulated mean gradient) and train_chain (the
        residual rides the scan carry).
      overlap: issue ONE masked collective per param leaf INSIDE the
        backward pass (``comm.allreduce.backward_psum_sync``) instead of a
        single fused psum at the end. Leaf k's collective then depends only
        on leaf k's backward subgraph, so the latency-hiding scheduler
        (TPU async all-reduce pairs) can hide it behind the remaining
        backward compute — SURVEY.md §8.4's overlap story. Composes with
        ``compress`` (bf16 psums; int8 = one per-leaf ring,
        ``backward_ring_sync``) AND ``error_feedback`` (the new residual
        rides the same autodiff pass as each leaf's e-cotangent —
        VERDICT r4 #4a); mutually exclusive only with ``bucket_size``
        (leaf granularity IS the bucketing).
    """

    def __init__(
        self,
        model,
        mesh: Mesh,
        example_input: np.ndarray,
        *,
        optimizer: optax.GradientTransformation | None = None,
        learning_rate: float = 0.1,
        bucket_size: int | None = None,
        loss_fn: Callable | None = None,
        seed: int = 0,
        compress: str | None = None,
        error_feedback: bool = False,
        overlap: bool = False,
    ) -> None:
        if overlap and bucket_size is not None:
            raise ValueError(
                "overlap issues ONE collective per param leaf inside the "
                "backward pass — leaf granularity IS its bucketing; "
                "bucket_size does not compose with it"
            )
        if compress not in (None, "bf16", "int8"):
            raise ValueError(
                f"compress must be None, 'bf16' or 'int8', got {compress!r}"
            )
        if compress == "int8" and len(mesh.axis_names) != 1:
            raise ValueError(
                "int8 grad sync rides the explicit ring schedule, which "
                f"reduces over ONE mesh axis; got axes {mesh.axis_names}"
            )
        if error_feedback and compress not in ("bf16", "int8"):
            raise ValueError(
                "error_feedback requires compress='bf16' or 'int8' "
                "(lossless sync has no residual to carry). bf16's cast "
                "error is exactly local (ef_residual); int8 EF is per-hop: "
                "the ring returns every quantization error this device "
                "injected — its own contribution's first hop AND its "
                "requantization of relayed partial sums — and the full "
                "amount is re-sent next step (VERDICT r4 #4c)"
            )
        self.model = model
        self.mesh = mesh
        self.axis_names = tuple(mesh.axis_names)
        self.n_devices = int(np.prod([mesh.shape[a] for a in self.axis_names]))
        self.tx = optimizer or optax.sgd(learning_rate)
        self.bucket_size = bucket_size
        self.compress = compress
        self.error_feedback = error_feedback
        self.overlap = overlap
        # how many independent data streams train_chain samples (one per
        # device here; the long-context trainer has one per DP replica row)
        self.data_shards = self.n_devices
        self._loss = loss_fn or default_classification_loss()

        key = jax.random.PRNGKey(seed)
        self.params = model.init(key, jnp.asarray(example_input))
        self.opt_state = self.tx.init(self.params)
        self.param_count = int(
            sum(np.prod(p.shape) for p in jax.tree.leaves(self.params))
        )
        self.step_num = 0

        data_spec = P(
            self.axis_names if len(self.axis_names) > 1 else self.axis_names[0]
        )
        self._data_spec = data_spec
        self._data_sharding = NamedSharding(mesh, data_spec)
        self._replicated = NamedSharding(mesh, P())
        axis_names = self.axis_names
        bucket = bucket_size
        model_apply = model.apply
        loss_impl = self._loss
        tx = self.tx
        wire_bf16 = compress == "bf16"
        n_devices_static = self.n_devices

        def explicit_step(params, opt_state, x, y, v, ef):
            """Explicit bucketed collective (the reference's chunked buffer):
            make params device-varying first so grads stay LOCAL (no implicit
            psum), then run the bucketed masked collective ourselves — in
            bfloat16 on the wire when compressing, with an optional
            error-feedback residual folded in and carried out."""
            scalar_cnt = lax.psum(v, axis_names)
            denom = jnp.maximum(scalar_cnt, 1.0)
            params_local = jax.tree.map(
                lambda p: lax.pcast(p, axis_names, to="varying"), params
            )

            def local_loss(p):
                logits = model_apply(p, x)
                return loss_impl(logits, y)

            loss, grads = jax.value_and_grad(local_loss)(params_local)
            flat, unravel = ravel_pytree(grads)
            c = ef_fold(flat, ef)
            b = bucket if bucket is not None else flat.shape[0]
            n_buckets = -(-flat.shape[0] // b)
            if compress == "int8":
                # quarter-width wire: the explicit ring carries int8 hops
                # with per-segment max-abs scales (comm/allreduce.py); the
                # ring segments by DEVICE COUNT, so bucket_size only sets
                # count granularity here, not wire chunking. Counts reuse
                # the scalar psum already computed above — no extra
                # collective on the hot path. With EF, the ring also
                # returns this device's PER-HOP injected quantization error
                # (partial-sum requantization included — VERDICT r4 #4c),
                # which becomes next step's residual: e' = c·(1−v) + hops.
                if ef is None:
                    gsum = ring_allreduce_sum(
                        c * v.astype(c.dtype),
                        axis_names[0],
                        n_devices_static,
                        compress="int8",
                    )
                    new_ef = None
                else:
                    gsum, hop_err = ring_allreduce_sum(
                        c * v.astype(c.dtype),
                        axis_names[0],
                        n_devices_static,
                        compress="int8",
                        return_residual=True,
                    )
                    new_ef = ring_ef_residual(c, v, hop_err).reshape(ef.shape)
                cnt = jnp.full((n_buckets,), scalar_cnt, jnp.float32)
            else:
                # bf16 wire: masked_psum runs the payload collective at half
                # width; counts stay float32 (exact at any mesh size)
                gsum, cnt = masked_psum(
                    c,
                    jnp.full((n_buckets,), v),
                    axis_names,
                    bucket_size=b,
                    wire_dtype=jnp.bfloat16 if wire_bf16 else None,
                )
                new_ef = None if ef is None else ef_residual(
                    c, v, ef, compress=compress
                )
            denom_el = jnp.maximum(expand_counts(cnt, flat.shape[0], b), 1.0)
            gavg = unravel(gsum / denom_el)
            loss_avg = lax.psum(loss * v, axis_names) / denom
            updates, new_opt = tx.update(gavg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt, new_ef, loss_avg, scalar_cnt

        if overlap:
            wire = jnp.bfloat16 if wire_bf16 else None
            if compress == "int8":
                # per-leaf int8 ring inside the backward (VERDICT r4 #4a)
                grad_sync = backward_ring_sync(
                    axis_names[0], n_devices_static, compress="int8"
                )
                grad_sync_ef = backward_ring_sync(
                    axis_names[0], n_devices_static, compress="int8",
                    error_feedback=True,
                ) if error_feedback else None
            else:
                grad_sync = backward_psum_sync(axis_names, wire)
                grad_sync_ef = (
                    backward_sync_ef(axis_names, wire)
                    if error_feedback
                    else None
                )

            def overlapped_step(params, opt_state, x, y, v, ef=None):
                """Per-leaf collectives issued INSIDE the backward pass:
                leaf k's psum (or int8 ring) depends only on leaf k's
                backward subgraph, so the latency-hiding scheduler can run
                it behind the rest of the backward (SURVEY.md §8.4;
                backward_psum_sync / backward_ring_sync). With EF, the
                flat residual is unraveled into param-shaped leaves, each
                leaf's sync folds its residual into the cotangent, and the
                NEW residual comes back as the e-cotangent of the same
                autodiff pass — e' = ravel of those leaves."""
                scalar_cnt = lax.psum(v, axis_names)
                denom = jnp.maximum(scalar_cnt, 1.0)
                params_local = jax.tree.map(
                    lambda p: lax.pcast(p, axis_names, to="varying"), params
                )
                if ef is None:

                    def local_loss(pt):
                        ps = jax.tree.map(lambda p: grad_sync(p, v), pt)
                        return loss_impl(model_apply(ps, x), y)

                    loss, gsum = jax.value_and_grad(local_loss)(params_local)
                    new_ef = None
                else:
                    _, unravel_p = ravel_pytree(params_local)
                    ef_tree = unravel_p(ef.reshape(-1))

                    def local_loss_ef(pt, et):
                        ps = jax.tree.map(
                            lambda p, e: grad_sync_ef(p, e, v), pt, et
                        )
                        return loss_impl(model_apply(ps, x), y)

                    loss, (gsum, new_ef_tree) = jax.value_and_grad(
                        local_loss_ef, argnums=(0, 1)
                    )(params_local, ef_tree)
                    new_ef = ravel_pytree(new_ef_tree)[0].reshape(ef.shape)
                gavg = jax.tree.map(lambda g: g / denom, gsum)
                loss_avg = lax.psum(loss * v, axis_names) / denom
                updates, new_opt = tx.update(gavg, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                if ef is None:
                    return new_params, new_opt, loss_avg, scalar_cnt
                return new_params, new_opt, new_ef, loss_avg, scalar_cnt

        def step(params, opt_state, x, y, valid):
            v = valid.reshape(())
            if overlap:
                return overlapped_step(params, opt_state, x, y, v)
            if bucket is not None or compress is not None:
                out = explicit_step(params, opt_state, x, y, v, None)
                return out[0], out[1], out[3], out[4]
            # Differentiating the v-weighted local loss w.r.t. REPLICATED
            # params makes JAX's shard_map autodiff insert the cross-device
            # psum itself (the transpose of the params broadcast), so the
            # gradient that comes back is already sum_d(v_d * g_d) in ONE
            # fused collective — the masked allreduce with zero extra code.
            scalar_cnt = lax.psum(v, axis_names)
            denom = jnp.maximum(scalar_cnt, 1.0)

            def global_masked_loss(p):
                logits = model_apply(p, x)
                return loss_impl(logits, y) * v

            lsum, gsum_tree = jax.value_and_grad(global_masked_loss)(params)
            gavg = jax.tree.map(lambda g: g / denom, gsum_tree)
            loss_avg = lax.psum(lsum, axis_names) / denom
            updates, new_opt = tx.update(gavg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt, loss_avg, scalar_cnt

        mapped = jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(P(), P(), data_spec, data_spec, data_spec),
            out_specs=(P(), P(), P(), P()),
            # the int8 ring's all-gather result IS replicated and the overlap
            # custom_vjp's psum erases vma typing, but the static varying-axes
            # check cannot see either (same caveat as the comm layer's ring
            # schedules); the f32-equivalence tests are the oracle
            check_vma=(compress != "int8" and not overlap),
        )
        self._step = jax.jit(mapped, donate_argnums=(0, 1))
        self._raw_step = step  # reused by train_chain's on-device loop

        if error_feedback:
            # per-device float32 residual of the compressed grad sync,
            # device-varying (each device carries ITS OWN withheld error)
            self._ef = jax.device_put(
                np.zeros((self.n_devices, self.param_count), np.float32),
                self._data_sharding,
            )

            def step_ef(params, opt_state, ef, x, y, valid):
                v = valid.reshape(())
                if overlap:
                    return overlapped_step(params, opt_state, x, y, v, ef)
                return explicit_step(params, opt_state, x, y, v, ef)

            self._raw_step_ef = step_ef  # reused by train_chain's EF loop
            self._step_ef = jax.jit(
                jax.shard_map(
                    step_ef,
                    mesh=mesh,
                    in_specs=(
                        P(), P(), data_spec, data_spec, data_spec, data_spec
                    ),
                    out_specs=(P(), P(), data_spec, P(), P()),
                    # the int8 ring's ppermute loop and the overlap
                    # custom_vjp erase varying-axes typing (same relaxation
                    # as the non-EF step above)
                    check_vma=(compress != "int8" and not overlap),
                ),
                donate_argnums=(0, 1, 2),
            )
        self._chains: dict = {}
        self._accum_steps_fns: dict = {}

        def eval_correct(params, x, y):
            logits = model_apply(params, x)
            hits = jnp.sum(jnp.argmax(logits, -1) == y)
            return lax.psum(hits, axis_names)

        self._eval = jax.jit(
            jax.shard_map(
                eval_correct,
                mesh=mesh,
                in_specs=(P(), data_spec, data_spec),
                out_specs=P(),
            )
        )

    # -- stepping ------------------------------------------------------------

    def _normalize_valid(self, valid: Sequence[float] | None) -> np.ndarray:
        return normalize_valid(valid, self.n_devices)

    def _place_batch(self, x, y):
        return place_batch(x, y, self.n_devices, self._data_sharding)

    def train_step(
        self, x: np.ndarray, y: np.ndarray, valid: Sequence[float] | None = None
    ) -> TrainStepMetrics:
        """One DP step. Single-process: ``x``/``y`` are the GLOBAL batch
        (first dim divisible by n_devices). Pod runtime (mesh spans OS
        processes): each process passes its HOST-LOCAL rows — see
        ``place_batch``; ``valid`` stays GLOBAL (n_devices,) either way."""
        valid_arr = self._normalize_valid(valid)
        xd, yd = self._place_batch(x, y)
        vd = place_mask(valid_arr, self._data_sharding)
        if self.error_feedback:
            self.params, self.opt_state, self._ef, loss, cnt = self._step_ef(
                self.params, self.opt_state, self._ef, xd, yd, vd
            )
            self.step_num += 1
            return TrainStepMetrics(
                step=self.step_num, loss=float(loss), contributors=float(cnt)
            )
        self.params, self.opt_state, loss, cnt = self._step(
            self.params, self.opt_state, xd, yd, vd
        )
        self.step_num += 1
        return TrainStepMetrics(
            step=self.step_num,
            loss=float(loss),
            contributors=float(cnt),
        )

    def train(
        self, batches: Iterable, valid_schedule: Callable[[int], Sequence[float]] | None = None
    ) -> list[TrainStepMetrics]:
        history = []
        for x, y in batches:
            valid = valid_schedule(self.step_num) if valid_schedule else None
            history.append(self.train_step(x, y, valid))
        return history

    def accuracy(self, x: np.ndarray, y: np.ndarray) -> float:
        xd, yd = self._place_batch(x, y)
        hits = self._eval(self.params, xd, yd)
        # the hit count is psummed over ALL devices, so the denominator is
        # the GLOBAL row count (xd is the assembled global array — on a pod
        # x.shape[0] would be only this process's rows)
        return float(hits) / xd.shape[0]

    # -- gradient accumulation (microbatching) -------------------------------

    def _build_accum_step(self, accum_steps: int):
        """One optimizer step over ``accum_steps`` microbatches: grads are
        accumulated per device across a ``lax.scan`` and synced with ONE
        masked psum at the end — bigger effective batches in fixed memory,
        and one collective per effective batch instead of per microbatch.
        Exactly equivalent to a single step on the concatenated batch (the
        mean of equal-size microbatch mean-gradients IS the full-batch mean).
        """
        axis_names = self.axis_names
        model_apply = self.model.apply
        loss_impl = self._loss
        tx = self.tx
        bucket = self.bucket_size
        ef_enabled = self.error_feedback

        def compute(params, opt_state, ef, x, y, valid):
            # x: (accum, micro, ...) per-device block; ef: residual or None
            v = valid.reshape(())
            scalar_cnt = lax.psum(v, axis_names)
            denom = jnp.maximum(scalar_cnt, 1.0)
            params_local = jax.tree.map(
                lambda p: lax.pcast(p, axis_names, to="varying"), params
            )

            def micro(carry, xy):
                g_acc, l_acc = carry
                xm, ym = xy

                def local_loss(p):
                    return loss_impl(model_apply(p, xm), ym)

                loss, grads = jax.value_and_grad(local_loss)(params_local)
                return (
                    jax.tree.map(jnp.add, g_acc, grads),
                    l_acc + loss,
                ), None

            zeros = jax.tree.map(jnp.zeros_like, params_local)
            # the loss carry must enter the scan device-varying like the
            # losses that accumulate into it
            l0 = lax.pcast(jnp.float32(0.0), axis_names, to="varying")
            (gsum, lsum), _ = lax.scan(micro, (zeros, l0), (x, y))
            # local mean over microbatches, then the SAME single fused (or
            # bucketed) masked collective the plain step uses — never one
            # psum per parameter leaf
            flat, unravel = ravel_pytree(
                jax.tree.map(lambda g: g / accum_steps, gsum)
            )
            # EF (train_step semantics on the accumulated mean gradient)
            c = ef_fold(flat, ef)
            wire = jnp.bfloat16 if self.compress == "bf16" else None
            if self.compress == "int8":
                # quarter-width wire at scan end: ONE int8 ring pass over
                # the accumulated mean gradient — the same explicit
                # collective the plain step uses, amortized over the whole
                # accumulation (VERDICT r3 #5a). Counts reuse the scalar
                # psum. EF composes per-hop exactly as in the plain step
                # (VERDICT r4 #4c): e' = c·(1−v) + ring hop errors.
                if ef is None:
                    total = ring_allreduce_sum(
                        c * v.astype(c.dtype),
                        axis_names[0],
                        self.n_devices,
                        compress="int8",
                    )
                    new_ef = None
                else:
                    total, hop_err = ring_allreduce_sum(
                        c * v.astype(c.dtype),
                        axis_names[0],
                        self.n_devices,
                        compress="int8",
                        return_residual=True,
                    )
                    new_ef = ring_ef_residual(c, v, hop_err).reshape(ef.shape)
                denom_el = denom  # per-element == scalar count (one ring)
            elif bucket is None:
                total, cnt = masked_psum(c, v, axis_names, wire_dtype=wire)
                denom_el = jnp.maximum(cnt, 1.0)
                new_ef = None if ef is None else ef_residual(
                    c, v, ef, compress=self.compress
                )
            else:
                n_buckets = -(-flat.shape[0] // bucket)
                total, cnt = masked_psum(
                    c,
                    jnp.full((n_buckets,), v),
                    axis_names,
                    bucket_size=bucket,
                    wire_dtype=wire,
                )
                denom_el = jnp.maximum(
                    expand_counts(cnt, flat.shape[0], bucket), 1.0
                )
                new_ef = None if ef is None else ef_residual(
                    c, v, ef, compress=self.compress
                )
            gavg = unravel(total / denom_el)
            loss_avg = lax.psum(lsum * v / accum_steps, axis_names) / denom
            updates, new_opt = tx.update(gavg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if ef is None:
                return new_params, new_opt, loss_avg, scalar_cnt
            return new_params, new_opt, new_ef, loss_avg, scalar_cnt

        data_spec = self._data_spec
        # the int8 ring's ppermute loop erases varying-axes typing (same
        # caveat as the comm layer's ring schedules); the f32-equivalence
        # test is the oracle there
        check_vma = self.compress != "int8"
        if ef_enabled:
            # compute already has the exact (params, opt, ef, x, y, valid)
            # signature; only the non-EF branch needs a wrapper to bind None
            mapped = jax.shard_map(
                compute,
                mesh=self.mesh,
                in_specs=(P(), P(), data_spec, data_spec, data_spec, data_spec),
                out_specs=(P(), P(), data_spec, P(), P()),
                check_vma=check_vma,
            )
            return jax.jit(mapped, donate_argnums=(0, 1, 2))

        def step(params, opt_state, x, y, valid):
            return compute(params, opt_state, None, x, y, valid)

        mapped = jax.shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(), P(), data_spec, data_spec, data_spec),
            out_specs=(P(), P(), P(), P()),
            check_vma=check_vma,
        )
        return jax.jit(mapped, donate_argnums=(0, 1))

    def train_step_accum(
        self,
        x: np.ndarray,
        y: np.ndarray,
        accum_steps: int,
        valid: Sequence[float] | None = None,
    ) -> TrainStepMetrics:
        """One optimizer step over a GLOBAL batch split into ``accum_steps``
        microbatches per device (batch divisible by n_devices * accum_steps).
        """
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        if accum_steps == 1:  # identical math; reuse the already-built step
            return self.train_step(x, y, valid)
        if self.overlap:
            raise NotImplementedError(
                "overlap is pointless under gradient accumulation: every "
                "leaf's gradient depends on the WHOLE accumulation scan, so "
                "per-leaf collectives could never run behind the backward; "
                "use the accumulation path without overlap"
            )
        if accum_steps not in self._accum_steps_fns:
            self._accum_steps_fns[accum_steps] = self._build_accum_step(
                accum_steps
            )
        sh = self._data_sharding
        valid_arr = self._normalize_valid(valid)
        if sh.is_fully_addressable:
            n = self.n_devices * accum_steps
            if x.shape[0] % n:
                raise ValueError(
                    f"global batch {x.shape[0]} not divisible by "
                    f"{self.n_devices} devices x {accum_steps} accumulation "
                    "steps"
                )
            micro = x.shape[0] // n
            # (global_batch, ...) -> (n_dev*accum, micro, ...): the data
            # sharding splits the leading axis, so device d gets its
            # contiguous (accum, micro, ...) block — the same rows
            # train_step would give it
            def rearrange(a, dt):
                a = np.asarray(a, dt)
                return a.reshape(n, micro, *a.shape[1:])

            xd = jax.device_put(rearrange(x, np.float32), sh)
            yd = jax.device_put(rearrange(y, np.int32), sh)
        else:
            # pod runtime (VERDICT r3 next-round #3): each process passes
            # its HOST-LOCAL rows; the (local_devices*accum, micro, ...)
            # layout is built locally and assembled into the global
            # microbatch array along the sharded leading axis —
            # jax.devices() is process-contiguous, so the assembly gives
            # every device the same contiguous block the single-controller
            # rearrange would
            from akka_allreduce_tpu.parallel import multihost

            mesh, spec = sh.mesh, sh.spec
            pid = jax.process_index()
            local_share = sum(
                1 for d in mesh.devices.flat if d.process_index == pid
            )
            ln = local_share * accum_steps
            if local_share == 0 or x.shape[0] % ln:
                raise ValueError(
                    f"host-local batch {x.shape[0]} not divisible by this "
                    f"process's {local_share} mesh devices x {accum_steps} "
                    "accumulation steps"
                )
            micro = x.shape[0] // ln

            def rearrange_local(a, dt):
                a = np.asarray(a, dt)
                return a.reshape(ln, micro, *a.shape[1:])

            xd = multihost.host_local_to_global(
                rearrange_local(x, np.float32), mesh, spec
            )
            yd = multihost.host_local_to_global(
                rearrange_local(y, np.int32), mesh, spec
            )
        vd = place_mask(valid_arr, sh)
        fn = self._accum_steps_fns[accum_steps]
        if self.error_feedback:
            self.params, self.opt_state, self._ef, loss, cnt = fn(
                self.params, self.opt_state, self._ef, xd, yd, vd
            )
        else:
            self.params, self.opt_state, loss, cnt = fn(
                self.params, self.opt_state, xd, yd, vd
            )
        self.step_num += 1
        return TrainStepMetrics(
            step=self.step_num, loss=float(loss), contributors=float(cnt)
        )

    # -- on-device training chain (data-loader path, no host I/O per step) ---

    def _build_chain(self, sampler, steps: int, batch_per_device: int):
        axis_names = self.axis_names

        def device_key(key):
            # independent per-device stream: fold the device's mesh
            # coordinates into the key (this IS the DP data shard)
            for a in axis_names:
                key = jax.random.fold_in(key, lax.axis_index(a))
            return key

        if self.error_feedback:
            raw_step_ef = self._raw_step_ef

            def chain_ef(params, opt_state, ef, key, valid):
                dkey = device_key(key)

                def body(carry, i):
                    p, o, e = carry
                    k = jax.random.fold_in(dkey, i)
                    x, y = sampler(k, batch_per_device)
                    p, o, e, loss, cnt = raw_step_ef(p, o, e, x, y, valid)
                    return (p, o, e), (loss, cnt)

                (params, opt_state, ef), (losses, cnts) = lax.scan(
                    body, (params, opt_state, ef), jnp.arange(steps)
                )
                return params, opt_state, ef, losses, cnts

            mapped = jax.shard_map(
                chain_ef,
                mesh=self.mesh,
                in_specs=(P(), P(), self._data_spec, P(), self._data_spec),
                out_specs=(P(), P(), self._data_spec, P(), P()),
                # same relaxations as _step_ef's shard_map: the int8
                # ring's ppermute loop and the overlap custom_vjp both
                # erase varying-axes typing (overlap composes with EF
                # since VERDICT r4 #4a)
                check_vma=(self.compress != "int8" and not self.overlap),
            )
            return jax.jit(mapped, donate_argnums=(0, 1, 2))

        raw_step = self._raw_step

        def chain(params, opt_state, key, valid):
            dkey = device_key(key)

            def body(carry, i):
                p, o = carry
                k = jax.random.fold_in(dkey, i)
                x, y = sampler(k, batch_per_device)
                p, o, loss, cnt = raw_step(p, o, x, y, valid)
                return (p, o), (loss, cnt)

            (params, opt_state), (losses, cnts) = lax.scan(
                body, (params, opt_state), jnp.arange(steps)
            )
            return params, opt_state, losses, cnts

        mapped = jax.shard_map(
            chain,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), self._data_spec),
            out_specs=(P(), P(), P(), P()),
            # same int8-ring / overlap caveat as the step's shard_map
            check_vma=(self.compress != "int8" and not self.overlap),
        )
        return jax.jit(mapped, donate_argnums=(0, 1))

    def train_chain(
        self,
        sampler,
        steps: int,
        batch_per_device: int,
        *,
        valid: Sequence[float] | None = None,
        seed: int = 0,
        fetch_metrics: bool = True,
    ) -> list[TrainStepMetrics] | tuple:
        """Run ``steps`` DP steps entirely on device in ONE dispatch.

        ``sampler`` is a traced ``(key, batch_size) -> (x, y)`` (e.g.
        ``SyntheticClassification.device_sampler``); each device draws its own
        batch shard per step, so no host->device transfer happens inside the
        loop — the data-loader discipline for tunneled/remote chips where a
        per-step host round trip costs more than the step itself.

        ``fetch_metrics=False`` returns the raw ``(losses, counts)`` device
        arrays instead of a metrics list — for benchmarks that must keep the
        O(steps) host fetch/conversion out of their timed window.
        """
        result = run_chain_cached(
            self,
            sampler,
            steps,
            batch_per_device,
            lambda: self._build_chain(sampler, steps, batch_per_device),
            valid,
            self.n_devices,
            self._data_sharding,
            seed,
            fetch_metrics=fetch_metrics,
            # the EF residual rides the scan carry and comes back as state
            extra_state=("_ef",) if self.error_feedback else (),
        )
        if not fetch_metrics:
            self.step_num += steps  # keep the data stream advancing
            return result
        losses, cnts = result
        out = []
        for loss, cnt in zip(losses, cnts):
            self.step_num += 1
            out.append(
                TrainStepMetrics(
                    step=self.step_num,
                    loss=float(loss),
                    contributors=float(cnt),
                )
            )
        return out

    # -- weights as a flat buffer (binder/checkpoint seam) -------------------

    def get_flat_params(self) -> np.ndarray:
        flat, _ = flatten_pytree(self.params)
        return flat

    def set_flat_params(self, vec: np.ndarray) -> None:
        _, unravel = ravel_pytree(self.params)
        self.params = jax.device_put(
            unravel(jnp.asarray(vec, jnp.float32)), self._replicated
        )
