"""Expert-parallel MoE trainer: DP x EP over a (data, expert) mesh, or
DP x SP x EP over (data, seq, expert) with ring/Ulysses attention.

Beyond-parity capability (the reference is DP-only, SURVEY.md §3). The dense
non-MoE parts treat the data and expert axes as data parallelism — the
global batch's rows shard over data x expert jointly (and its sequence over
``seq`` when present) — while each MoE layer's all_to_all pair (ops/moe.py)
rides the ``expert`` axis. Gradient plumbing reuses the
framework's one mechanism: expert weights enter shard_map device-varying on
``expert`` (ep_param_specs), so shard_map autodiff psums their grads over
``data`` only; replicated leaves psum over both axes — the threshold-masked
allreduce with the same contributor-mask semantics as every other trainer
(mask per DP replica row).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class MoEStepMetrics:
    step: int
    loss: float  # masked per-token cross-entropy (aux not included)
    aux_loss: float  # Switch load-balancing loss (global weighted mean)
    dropped: float  # fraction of routing ASSIGNMENTS past expert capacity
    # (denominator k*T under top-k) — the capacity_factor tuning knob
    contributors: float  # contributing DP replica rows


class MoETrainer:
    """DP (x EP) trainer for :class:`~akka_allreduce_tpu.models.MoETransformerLM`.

    Args:
      mesh: a 1-axis (data,) mesh for dense MoE, a 2-axis (data, expert)
        mesh for expert parallelism, or a 3-axis (data, seq, expert) mesh
        composing sequence parallelism with EP — ring/Ulysses attention
        shards the sequence over ``seq`` while each MoE layer's all_to_all
        rides ``expert``. Routing stays per-device, so expert capacity is
        computed over LOCAL tokens (T/sp per device), while the aux
        load-balancing statistics are psum-averaged over the seq shards —
        so with ample ``capacity_factor`` the whole step is exactly
        partition-independent (the tests' oracle); under capacity pressure,
        drops depend on the sharding, as in any capacity-based MoE system.
      seq_len: GLOBAL per-sample sequence length (divisible by the seq
        axis size when present).
      aux_coef: weight of the Switch load-balancing loss.
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        vocab: int = 64,
        d_model: int = 64,
        n_heads: int = 4,
        n_kv_heads: int | None = None,
        n_layers: int = 2,
        n_experts: int = 4,
        seq_len: int = 64,
        capacity_factor: float = 1.25,
        router_topk: int = 1,
        seq_impl: str = "ring",
        aux_coef: float = 0.01,
        optimizer: optax.GradientTransformation | None = None,
        learning_rate: float = 1e-2,
        mu_dtype=None,
        seed: int = 0,
        compute_dtype=jnp.float32,
        compress: str | None = None,
        overlap: bool = False,
        dispatch_impl: str = "auto",
    ) -> None:
        from akka_allreduce_tpu.models.transformer import (
            MoETransformerLM,
            ep_param_specs,
        )

        from akka_allreduce_tpu.comm.allreduce import validate_trainer_compress

        self.compress = validate_trainer_compress(compress, overlap=overlap)
        self.overlap = overlap

        if len(mesh.axis_names) not in (1, 2, 3):
            raise ValueError(
                f"need a (data[, expert] | data, seq, expert) mesh, got "
                f"axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.data_axis = mesh.axis_names[0]
        if len(mesh.axis_names) == 3:
            # (data, seq, expert): sequence parallelism composed with EP —
            # ring/Ulysses attention over `seq`, expert all_to_all over
            # `expert`, the dense parts data-parallel over data x expert
            self.seq_axis = mesh.axis_names[1]
            self.expert_axis = mesh.axis_names[2]
        else:
            self.seq_axis = None
            self.expert_axis = (
                mesh.axis_names[1] if len(mesh.axis_names) == 2 else None
            )
        self.dp = int(mesh.shape[self.data_axis])
        self.sp = int(mesh.shape[self.seq_axis]) if self.seq_axis else 1
        self.ep = int(mesh.shape[self.expert_axis]) if self.expert_axis else 1
        if n_experts % self.ep:
            raise ValueError(f"{n_experts=} not divisible by ep={self.ep}")
        if seq_len % self.sp:
            raise ValueError(
                f"{seq_len=} not divisible by seq shards {self.sp}"
            )
        self.n_devices = self.dp * self.sp * self.ep
        self.data_shards = self.dp
        self.seq_len = seq_len
        self.vocab = vocab
        self.aux_coef = aux_coef
        self.model = MoETransformerLM(
            vocab=vocab,
            d_model=d_model,
            n_heads=n_heads,
            n_kv_heads=n_kv_heads,
            n_layers=n_layers,
            n_experts=n_experts,
            capacity_factor=capacity_factor,
            compute_dtype=compute_dtype,
            expert_axis=self.expert_axis if self.ep > 1 else None,
            ep_size=self.ep,
            router_topk=router_topk,
            seq_axis=self.seq_axis if self.sp > 1 else None,
            seq_impl=seq_impl,
            dispatch_impl=dispatch_impl,
        )
        # mu_dtype=bfloat16 halves the first-moment read+write traffic of
        # the adam update — the LARGEST single cost of a single-chip MoE
        # step, because the optimizer touches ALL E experts' params every
        # step while only the active ones did compute (xprof breakdown in
        # BENCHMARKS.md round 4); nu (the variance) stays f32
        self.tx = optimizer or optax.adam(learning_rate, mu_dtype=mu_dtype)

        # full-shape init (ep=1 twin); shard_map in_specs slice expert leaves
        init_model = MoETransformerLM(
            vocab=vocab,
            d_model=d_model,
            n_heads=n_heads,
            n_kv_heads=n_kv_heads,
            n_layers=n_layers,
            n_experts=n_experts,
            capacity_factor=capacity_factor,
            compute_dtype=compute_dtype,
            router_topk=router_topk,
        )
        tokens0 = jnp.zeros((1, seq_len // self.sp), jnp.int32)
        self.params = init_model.init(jax.random.PRNGKey(seed), tokens0)
        self.opt_state = self.tx.init(self.params)
        self.param_count = int(
            sum(np.prod(p.shape) for p in jax.tree.leaves(self.params))
        )
        self.step_num = 0

        if self.ep > 1:
            assert self.expert_axis is not None
            self._param_specs = ep_param_specs(self.params, self.expert_axis)
            self._opt_specs = ep_param_specs(self.opt_state, self.expert_axis)
        else:
            self._param_specs = jax.tree.map(lambda _: P(), self.params)
            self._opt_specs = jax.tree.map(lambda _: P(), self.opt_state)
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        self.params = jax.device_put(
            self.params,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), self._param_specs,
                is_leaf=is_spec,
            ),
        )
        self.opt_state = jax.device_put(
            self.opt_state,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), self._opt_specs,
                is_leaf=is_spec,
            ),
        )

        axis_names = tuple(mesh.axis_names)
        if self.seq_axis is not None:
            # rows over data x expert, the sequence dim over seq
            batch_spec = P((self.data_axis, self.expert_axis), self.seq_axis)
        elif len(axis_names) > 1:
            batch_spec = P(axis_names)
        else:
            batch_spec = P(axis_names[0])
        self._data_sharding = NamedSharding(mesh, batch_spec)
        self._valid_sharding = NamedSharding(mesh, P(self.data_axis))
        data_axis = self.data_axis
        vary_axes = tuple(n for n in axis_names if n != data_axis)
        model_apply = self.model.apply
        tx = self.tx
        aux_coef = self.aux_coef
        param_specs = self._param_specs
        wire_dtype = jnp.bfloat16 if compress == "bf16" else None

        def step(params, opt_state, x, y, valid):
            v0 = valid.reshape(())
            v = v0
            for ax in vary_axes:
                v = lax.pcast(v, ax, to="varying")
            tokens_local = jnp.float32(x.shape[0] * x.shape[1])
            denom = jnp.maximum(lax.psum(v * tokens_local, axis_names), 1.0)

            def masked_loss(p):
                logits, aux, dropped = model_apply(p, x)
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).sum()
                # aux is a per-device mean: weight by local tokens so the
                # global sum / denom is its masked token-weighted mean
                total = (ce + aux_coef * aux * tokens_local) * v / denom
                return total, (ce, aux, dropped)

            if overlap:
                # per-leaf in-backward collectives (SURVEY.md §8.4): the
                # loss is UNMASKED — each leaf's sync masks its cotangent
                # itself; the metric psums below re-apply v explicitly
                from akka_allreduce_tpu.comm.allreduce import (
                    overlap_value_and_grad,
                )

                def unmasked_loss(ps):
                    logits, aux, dropped = model_apply(ps, x)
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        logits, y
                    ).sum()
                    total = (ce + aux_coef * aux * tokens_local) / denom
                    return total, (ce, aux, dropped)

                (_, (ce, aux, dropped)), gavg = overlap_value_and_grad(
                    unmasked_loss, params, param_specs, axis_names, v,
                    has_aux=True, wire_dtype=wire_dtype,
                )
            elif compress in ("bf16", "int8"):
                # explicit grouped collective (see long_context.py);
                # expert-sharded leaves reduce over data/seq only; int8
                # rides the explicit ring per reduce axis
                from akka_allreduce_tpu.comm.allreduce import (
                    compressed_value_and_grad,
                )

                (_, (ce, aux, dropped)), gavg = compressed_value_and_grad(
                    masked_loss, params, param_specs, axis_names,
                    has_aux=True,
                    wire_dtype=compress,
                )
            else:
                # explicit grouped psums even uncompressed: the automatic
                # transpose-psum for replicated params does not run under
                # check_vma=False (flash-relax configs) — see
                # long_context.py / tests/test_vma_replication.py
                from akka_allreduce_tpu.comm.allreduce import (
                    compressed_value_and_grad,
                )

                (_, (ce, aux, dropped)), gavg = compressed_value_and_grad(
                    masked_loss, params, param_specs, axis_names,
                    has_aux=True,
                    wire_dtype=None,
                )
            loss_avg = lax.psum(ce * v / denom, axis_names)
            aux_avg = lax.psum(aux * tokens_local * v / denom, axis_names)
            dropped_avg = lax.psum(
                dropped * tokens_local * v / denom, axis_names
            )
            contributors = lax.psum(v0, data_axis)
            updates, new_opt = tx.update(gavg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return (
                new_params, new_opt, loss_avg, aux_avg, dropped_avg,
                contributors,
            )

        from akka_allreduce_tpu.ops.local_attention import flash_vma_relax

        self._check_vma = not overlap and compress != "int8" and not flash_vma_relax(
            seq_len, d_model // n_heads, sp=self.sp, seq_impl=seq_impl
        )
        mapped = jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(
                self._param_specs,
                self._opt_specs,
                batch_spec,
                batch_spec,
                P(self.data_axis),
            ),
            out_specs=(self._param_specs, self._opt_specs, P(), P(), P(), P()),
            # off when the overlap custom_vjp erases varying-axes typing OR
            # the flash kernel can dispatch (outputs carry no vma —
            # ops.local_attention.flash_vma_relax, LongContext's discipline)
            check_vma=self._check_vma,
        )
        self._step = jax.jit(mapped, donate_argnums=(0, 1))
        self._raw_step = step  # reused by train_chain's on-device loop
        self._replicated = NamedSharding(mesh, P())
        self._chains: dict = {}

    # -- stepping ------------------------------------------------------------

    def train_step(
        self,
        tokens: np.ndarray,
        labels: np.ndarray,
        valid: Sequence[float] | None = None,
    ) -> MoEStepMetrics:
        """One step on a GLOBAL (batch, seq_len) token array; batch divisible
        by dp * ep. ``valid``: per-DP-replica-row mask of shape (dp,)."""
        row_shards = self.dp * self.ep  # rows shard over data x expert only
        if (
            self._data_sharding.is_fully_addressable
            and tokens.shape[0] % row_shards
        ):
            # pod runtime: callers pass HOST-LOCAL rows, so the global
            # divisibility check belongs to place_tokens' seam, not here
            raise ValueError(
                f"global batch {tokens.shape[0]} not divisible by "
                f"{row_shards} row shards (data x expert)"
            )
        if tokens.shape[1] != self.seq_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} != {self.seq_len}"
            )
        from akka_allreduce_tpu.train.trainer import (
            normalize_valid,
            place_mask,
            place_tokens,
        )

        valid_arr = normalize_valid(valid, self.dp)
        xd, yd = place_tokens(
            tokens, labels, self._data_sharding,
            seq_len=self.seq_len, dp=1,  # row divisibility checked above
        )
        vd = place_mask(valid_arr, self._valid_sharding)
        self.params, self.opt_state, loss, aux, dropped, cnt = self._step(
            self.params, self.opt_state, xd, yd, vd
        )
        self.step_num += 1
        return MoEStepMetrics(
            step=self.step_num,
            loss=float(loss),
            aux_loss=float(aux),
            dropped=float(dropped),
            contributors=float(cnt),
        )

    def train(self, batches: Iterable) -> list[MoEStepMetrics]:
        return [self.train_step(x, y) for x, y in batches]

    # -- on-device training chain (no host I/O per step) ---------------------

    def _build_chain(self, sampler, steps: int, rows_per_device: int):
        raw_step = self._raw_step
        data_axis, expert_axis = self.data_axis, self.expert_axis
        seq_axis = self.seq_axis
        t_local = self.seq_len // self.sp

        def chain(params, opt_state, key, valid):
            # one independent stream per (data, expert) COORDINATE: both
            # those axes carry data rows for the dense parts. On the 3-axis
            # mesh the seq shards of a coordinate fold the SAME key — they
            # must agree on the rows' tokens — and each slices its own
            # T_local columns from the sampler's GLOBAL sequences
            # (LongContextTrainer._build_chain's discipline)
            rkey = jax.random.fold_in(key, lax.axis_index(data_axis))
            if expert_axis is not None:
                rkey = jax.random.fold_in(rkey, lax.axis_index(expert_axis))
            s = lax.axis_index(seq_axis) if seq_axis is not None else None

            def body(carry, i):
                p, o = carry
                k = jax.random.fold_in(rkey, i)
                x, y = sampler(k, rows_per_device)
                if s is not None:
                    x = lax.dynamic_slice_in_dim(
                        x, s * t_local, t_local, axis=1
                    )
                    y = lax.dynamic_slice_in_dim(
                        y, s * t_local, t_local, axis=1
                    )
                p, o, loss, aux, dropped, cnt = raw_step(p, o, x, y, valid)
                return (p, o), (loss, aux, dropped, cnt)

            (params, opt_state), outs = lax.scan(
                body, (params, opt_state), jnp.arange(steps)
            )
            return params, opt_state, *outs

        mapped = jax.shard_map(
            chain,
            mesh=self.mesh,
            in_specs=(
                self._param_specs,
                self._opt_specs,
                P(),
                P(self.data_axis),
            ),
            out_specs=(
                self._param_specs,
                self._opt_specs,
                P(),
                P(),
                P(),
                P(),
            ),
            # same vma caveats as the step's shard_map (overlap / flash)
            check_vma=self._check_vma,
        )
        return jax.jit(mapped, donate_argnums=(0, 1))

    def train_chain(
        self,
        sampler,
        steps: int,
        rows_per_device: int,
        *,
        valid: Sequence[float] | None = None,
        seed: int = 0,
    ) -> list[MoEStepMetrics]:
        """Run ``steps`` DP x EP (x SP) steps entirely on device in ONE
        dispatch.

        ``sampler`` is a traced ``(key, rows) -> (tokens, labels)``
        producing GLOBAL (rows, seq_len) sequences (e.g.
        ``SyntheticCopyLM.device_sampler``); each (data, expert) coordinate
        draws its own stream and, on the 3-axis mesh, its seq shards slice
        their local columns — zero host I/O either way.
        """
        from akka_allreduce_tpu.train.trainer import run_chain_cached

        losses, auxes, droppeds, cnts = run_chain_cached(
            self,
            sampler,
            steps,
            rows_per_device,
            lambda: self._build_chain(sampler, steps, rows_per_device),
            valid,
            self.dp,
            self._valid_sharding,
            seed,
        )
        out = []
        for loss, aux, dropped, cnt in zip(losses, auxes, droppeds, cnts):
            self.step_num += 1
            out.append(
                MoEStepMetrics(
                    step=self.step_num,
                    loss=float(loss),
                    aux_loss=float(aux),
                    dropped=float(dropped),
                    contributors=float(cnt),
                )
            )
        return out

    def get_flat_params(self) -> np.ndarray:
        from akka_allreduce_tpu.binder.api import flatten_pytree

        return flatten_pytree(self.params)[0]
