"""FSDP / ZeRO-3 LM trainer: params AND optimizer state sharded 1/n.

Beyond-parity capability (the reference is DP-only, SURVEY.md §3), completing
the ZeRO family next to ``Zero1DPTrainer``: stage 1 shards only the optimizer
state; this shards the trunk *parameters* too, so per-device memory for the
model's bulk is ``(params + moments)/n`` — the knob that lets a data-parallel
group train models larger than one chip's HBM.

Built the TPU way, on the same stacked-trunk substrate as the pipeline
trainer: the transformer trunk's L layers stack into one params tree with a
leading layer dim, and each trunk leaf ``(L, *S)`` is stored flattened and
sharded ``(L, n, per)`` with ``P(None, 'data')`` — device d holds the d-th
1/n slice of EVERY layer. The forward is a ``lax.scan`` over layers whose
body ``all_gather``s ONE layer's shards into the full layer, applies the
block, and discards the gathered copy — so a full layer is materialized only
transiently. Autodiff does the rest: the transpose of a tiled ``all_gather``
IS ``psum_scatter``, so each layer's gradient arrives reduce-scattered,
shard-local, exactly ZeRO-3's gradient flow, with no hand-written collective.
``remat=True`` additionally recomputes each layer on backward (one layer's
activations + one layer's params live at a time — the full FSDP memory
profile).

Embed/head (the small edge leaves) stay replicated with the standard
transpose-psum gradient, like every other trainer here. Threshold masking is
per DP device, the same contributor semantics as DPTrainer.

Checkpoints serialize the trunk UNSHARDED (gather-then-reshard at checkpoint
scale, the ZeRO-1 discipline), so an n-device checkpoint restores onto any
other device count.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.models.transformer import Block
from akka_allreduce_tpu.train.pipeline import _LMHead
from akka_allreduce_tpu.train.trainer import (
    TrainStepMetrics,
    normalize_valid,
    place_mask,
    place_tokens,
)


def _shard_leaf(leaf: jax.Array, n: int) -> jax.Array:
    """(L, *S) -> (L, n, per): flatten, pad to n equal slices per layer."""
    flat = leaf.reshape(leaf.shape[0], -1)
    per = -(-flat.shape[1] // n)
    return jnp.pad(flat, ((0, 0), (0, per * n - flat.shape[1]))).reshape(
        leaf.shape[0], n, per
    )


def _unshard_leaf(leaf: jax.Array, full_shape: tuple) -> jax.Array:
    """(L, n, per) -> (L, *S): inverse of :func:`_shard_leaf`."""
    size = int(np.prod(full_shape[1:]))
    return leaf.reshape(leaf.shape[0], -1)[:, :size].reshape(full_shape)


def _shard_leaf_tp(
    leaf: jax.Array, n: int, tp: int, tp_dim: int
) -> jax.Array:
    """(L, *S) -> (L, tp, n, per) for a tensor-parallel trunk leaf: split
    the Megatron-sharded dim (``tp_dim``, 0-based within the per-layer
    shape) into ``tp`` slices, flatten each slice's remaining dims in
    original order, and pad to ``n`` equal FSDP shards. Dim 1 shards over
    ``model``, dim 2 over the gather (data[, seq]) axes — so each device
    stores 1/(tp*n) of every layer and the in-scan all_gather over the
    gather axes reassembles exactly this model shard's TP-LOCAL layer."""
    length, s = leaf.shape[0], leaf.shape[1:]
    loc = s[tp_dim] // tp
    x = leaf.reshape(
        *leaf.shape[: 1 + tp_dim], tp, loc, *s[tp_dim + 1 :]
    )
    x = jnp.moveaxis(x, 1 + tp_dim, 1)  # (L, tp, ...S with loc at tp_dim...)
    flat = x.reshape(length, tp, -1)
    per = -(-flat.shape[2] // n)
    return jnp.pad(
        flat, ((0, 0), (0, 0), (0, per * n - flat.shape[2]))
    ).reshape(length, tp, n, per)


def _unshard_leaf_tp(
    leaf: jax.Array, full_shape: tuple, tp_dim: int
) -> jax.Array:
    """(L, tp, n, per) -> (L, *S): inverse of :func:`_shard_leaf_tp`.

    Module-agnostic: numpy input stays on host (the checkpoint writer
    thread unshards captured host leaves without touching a device)."""
    xp = jnp if isinstance(leaf, jax.Array) else np
    length = leaf.shape[0]
    tp = leaf.shape[1]
    s = full_shape[1:]
    loc = s[tp_dim] // tp
    local_s = s[:tp_dim] + (loc,) + s[tp_dim + 1 :]
    size = int(np.prod(local_s))
    x = leaf.reshape(length, tp, -1)[:, :, :size].reshape(
        length, tp, *local_s
    )
    x = xp.moveaxis(x, 1, 1 + tp_dim)
    return x.reshape(full_shape)


class FSDPLMTrainer:
    """Fully-sharded data-parallel trainer for a decoder-only LM.

    Args:
      mesh: a 1-axis (data,) mesh, or a 2-axis (data, seq) mesh — FSDP x SP,
        the modern long-context recipe: params shard over the WHOLE mesh
        (dp*sp slices) while ring/Ulysses attention shards the sequence over
        ``seq``.
      n_layers: trunk depth (the FSDP-sharded bulk).
      seq_impl: attention schedule over the seq axis ("ring" | "ulysses"),
        used when the mesh has one.
      remat: ``True`` (or ``"full"``) recomputes each layer on backward
        (jax.checkpoint — one layer's activations at a time, maximum memory
        savings, ~1 extra forward of FLOPs). ``"params"`` drops the
        gathered full-layer params from the residuals and re-gathers them
        on backward (``dots_saveable`` policy: matmul outputs — the
        layer's real activations — stay saved; the gather chain and cheap
        elementwise ops recompute). This is the ZeRO-3 sweet spot when
        activations fit: without it the scan saves every iteration's
        gathered layer (L full layers resident — the no-remat OOM), with
        full remat the step pays ~25-30 % MFU for matmul recompute the
        model didn't need.
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        vocab: int = 64,
        d_model: int = 64,
        n_heads: int = 4,
        n_kv_heads: int | None = None,
        n_layers: int = 2,
        seq_len: int = 64,
        seq_impl: str = "ring",
        optimizer: optax.GradientTransformation | None = None,
        learning_rate: float = 1e-2,
        seed: int = 0,
        compute_dtype=jnp.float32,
        remat: bool | str = False,
        compress: str | None = None,
        prefetch: bool = False,
    ) -> None:
        if remat is True:
            remat = "full"
        if remat not in (False, "full", "params"):
            raise ValueError(
                f"remat must be False, True/'full', or 'params', got {remat!r}"
            )
        axes = tuple(mesh.axis_names)
        # accepted meshes (by axis NAME — "model" selects Megatron TP, in
        # ANY order after the leading data axis, so the repo's canonical
        # data_seq_model_mesh layout with model innermost works too):
        #   (data,) | (data, seq) | (data, model) | (data, {model, seq})
        ok = (
            len(axes) in (1, 2, 3)
            and axes[0] not in ("model", "seq")
            and set(axes[1:]) <= {"model", "seq"}
            and len(set(axes)) == len(axes)
        )
        if not ok:
            raise ValueError(
                "FSDP needs a (data[, model][, seq]) mesh — leading data "
                "axis, then any of 'model' (Megatron TP) and 'seq' — got "
                f"{axes}"
            )
        if compress not in (None, "bf16", "int8"):
            raise ValueError(
                f"compress must be None, 'bf16' or 'int8', got {compress!r}"
            )
        if prefetch and remat == "full":
            raise ValueError(
                "prefetch and full remat do not compose: the prefetched "
                "gathered layer rides the scan CARRY, and scan saves every "
                "iteration's carry as a backward residual — all L gathered "
                "layers would stay resident, defeating exactly the memory "
                "profile full remat buys. prefetch DOES compose with "
                "remat='params' (the trunk unrolls so forward AND backward "
                "re-gathers can run behind neighboring layers' matmuls)"
            )
        self.compress = compress
        self.prefetch = prefetch
        self.mesh = mesh
        self.axes = axes
        self.data_axis = axes[0]
        self.model_axis = "model" if "model" in axes else None
        self.seq_axis = "seq" if "seq" in axes else None
        # params gather over every NON-model axis: each Megatron shard
        # FSDP-shards (and re-gathers) only its own tp-local slice
        self.gather_axes = tuple(a for a in axes if a != self.model_axis)
        self.dp = int(mesh.shape[self.data_axis])
        self.sp = int(mesh.shape[self.seq_axis]) if self.seq_axis else 1
        self.tp = int(mesh.shape[self.model_axis]) if self.model_axis else 1
        self.n_devices = self.dp * self.sp * self.tp
        n = self.dp * self.sp  # FSDP shards per tp-local slice
        self.gather_shards = n
        self.data_shards = self.dp
        if seq_len % self.sp:
            raise ValueError(
                f"{seq_len=} not divisible by seq shards {self.sp}"
            )
        self.seq_len = seq_len
        self.vocab = vocab
        self.n_layers = n_layers
        self.tx = optimizer or optax.adam(learning_rate)

        block = Block(
            n_heads=n_heads,
            n_kv_heads=n_kv_heads,
            compute_dtype=compute_dtype,
            seq_axis=self.seq_axis if self.sp > 1 else None,
            seq_impl=seq_impl,
            model_axis=self.model_axis if self.tp > 1 else None,
            tp_size=self.tp,
        )
        embed = nn.Embed(vocab, d_model, dtype=compute_dtype)
        head = _LMHead(vocab, compute_dtype=compute_dtype)
        rng = jax.random.PRNGKey(seed)
        # init with the DENSE twin (param shapes are T- and axis-independent)
        init_block = Block(
            n_heads=n_heads, n_kv_heads=n_kv_heads,
            compute_dtype=compute_dtype,
        )
        x0 = jnp.zeros((1, seq_len // self.sp, d_model), jnp.float32)
        tok0 = jnp.zeros((1, seq_len // self.sp), jnp.int32)
        layer_ps = [
            init_block.init(jax.random.fold_in(rng, 1000 + i), x0)["params"]
            for i in range(n_layers)
        ]
        trunk_full = jax.tree.map(lambda *ls: jnp.stack(ls), *layer_ps)
        # static pytree of full trunk shapes, for the in-scan ungather
        # (tuple leaves survive tree.map via flatten_up_to; never
        # jax.tree.leaves this tree — the tuples would flatten into ints)
        self._trunk_shapes = jax.tree.map(lambda l: l.shape, trunk_full)
        # per-leaf Megatron dim (0-based within the per-layer shape; -1 =
        # replicated across model — None would vanish as an empty pytree)
        # from the SAME rule tp_param_specs uses, so the FSDP storage can
        # never drift from the TP module's layout
        if self.tp > 1:
            from akka_allreduce_tpu.models.transformer import tp_param_specs

            tp_specs = tp_param_specs(layer_ps[0], self.model_axis)
            self._trunk_tp_dims = jax.tree.map(
                lambda s: (
                    s.index(self.model_axis) if self.model_axis in s else -1
                ),
                tp_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        else:
            self._trunk_tp_dims = jax.tree.map(lambda _: -1, layer_ps[0])
        tp = self.tp

        def store_leaf(leaf, tp_dim):
            if tp_dim < 0:
                return _shard_leaf(leaf, n)
            return _shard_leaf_tp(leaf, n, tp, tp_dim)

        # local (this model shard's) per-layer shapes, for the in-scan
        # ungather: the TP dim shrinks by tp on Megatron-sharded leaves
        def local_shape(shape, tp_dim):
            if tp_dim < 0:
                return shape
            s = list(shape)
            s[1 + tp_dim] //= tp
            return tuple(s)

        self._trunk_local_shapes = jax.tree.map(
            local_shape, self._trunk_shapes, self._trunk_tp_dims,
            is_leaf=lambda x: isinstance(x, tuple),
        )
        trunk_count = int(sum(l.size for l in jax.tree.leaves(trunk_full)))
        self.params = {
            "embed": embed.init(jax.random.fold_in(rng, 1), tok0)["params"],
            "trunk": jax.tree.map(
                store_leaf, trunk_full, self._trunk_tp_dims
            ),
            "head": head.init(jax.random.fold_in(rng, 2), x0)["params"],
        }
        self.param_count = trunk_count + int(
            sum(
                np.prod(p.shape)
                for k in ("embed", "head")
                for p in jax.tree.leaves(self.params[k])
            )
        )
        self.opt_state = self.tx.init(self.params)

        gather_axes = self.gather_axes

        def spec_for(path, leaf):
            names = [
                str(getattr(k, "key", getattr(k, "name", k))) for k in path
            ]
            if "trunk" in names and np.ndim(leaf) == 4:
                # (L, tp, n, per): Megatron slice dim on `model`, FSDP
                # shard dim jointly over the gather axes
                return P(None, self.model_axis, gather_axes)
            if "trunk" in names and np.ndim(leaf) == 3:
                # (L, n, per): shard dim 1 over the gather axes (data-major,
                # matching the tuple-axis all_gather order in the scan
                # body); model-replicated when a model axis exists
                return P(None, gather_axes)
            return P()

        self._param_specs = jax.tree_util.tree_map_with_path(
            spec_for, self.params
        )
        self._opt_specs = jax.tree_util.tree_map_with_path(
            spec_for, self.opt_state
        )
        self.params = self._place(self.params, self._param_specs)
        self.opt_state = self._place(self.opt_state, self._opt_specs)
        self._replicated = NamedSharding(mesh, P())
        batch_spec = (
            P(self.data_axis, self.seq_axis)
            if self.seq_axis
            else P(self.data_axis)
        )
        self._data_sharding = NamedSharding(mesh, batch_spec)
        self._valid_sharding = NamedSharding(mesh, P(self.data_axis))
        self.step_num = 0

        axes = self.axes
        data_axis = self.data_axis
        seq_axis = self.seq_axis
        vary_axes = tuple(a for a in axes if a != data_axis)
        g_axes = self.gather_axes
        param_specs = self._param_specs
        # the in-scan ungather targets THIS model shard's local layer
        # shapes (the TP dim shrinks by tp on Megatron-sharded leaves)
        trunk_shapes = self._trunk_local_shapes
        block_apply = block.apply
        embed_apply = embed.apply
        head_apply = head.apply
        tx = self.tx

        int8_gather = None
        if compress == "int8":
            from akka_allreduce_tpu.comm.allreduce import (
                ring_reduce_scatter_sum,
            )
            from akka_allreduce_tpu.ops.ring import int8_quantize

            n_shards = self.gather_shards
            # tile order of a multi-axis tiled all_gather is row-major over
            # the axis tuple (first axis outermost), so its transpose
            # decomposes into SEQUENTIAL per-axis rings: reduce-scatter the
            # outer axis first (segments of inner_size*shard), then the
            # inner axis — each ring carries int8 per-hop payloads. This
            # closes the old FSDP x SP exclusion (VERDICT r4 #4b): gathers
            # over (data, seq) now run quarter-width both ways.
            axis_sizes = [int(self.mesh.shape[a]) for a in g_axes]

            @jax.custom_vjp
            def int8_gather(flat):
                q, sc = int8_quantize(flat)
                qf = lax.all_gather(q, g_axes, tiled=True)
                scf = lax.all_gather(sc.reshape(1), g_axes, tiled=True)
                return (
                    qf.reshape(n_shards, -1).astype(jnp.float32)
                    * scf[:, None]
                ).reshape(-1)

            def _fwd(flat):
                return int8_gather(flat), None

            def _bwd(_, ct):
                # the all_gather's transpose is reduce-scatter; ride the
                # explicit int8 ring(s) so the backward wire is
                # quarter-width too (per-hop scales; ct length =
                # prod(axis_sizes) * shard, so segments align with the
                # tiled gather layout exactly, outer axis first)
                out = ct
                for ax, sz in zip(g_axes, axis_sizes):
                    out = ring_reduce_scatter_sum(
                        out, ax, sz, compress="int8"
                    )
                return (out,)

            int8_gather.defvjp(_fwd, _bwd)

        def step(params, opt_state, x, y, valid):
            v0 = valid.reshape(())
            v = v0
            for ax in vary_axes:
                # the mask is per DP replica row; mark it varying on the
                # seq/model axes so the all-axes psums below are well-typed
                # (LongContext's discipline — under TP every model shard of
                # a data coordinate computes the identical loss term, so
                # the tp-fold factors cancel in the ratio)
                v = lax.pcast(v, ax, to="varying")
            contributors = lax.psum(v0, data_axis)
            tokens_local = jnp.float32(x.shape[0] * x.shape[1])
            denom = jnp.maximum(lax.psum(v * tokens_local, axes), 1.0)

            def masked_loss(p):
                h = embed_apply({"params": p["embed"]}, x)

                def gather_leaf(s, shape):
                    # gather ONE layer's shard over the NON-model axes —
                    # the all_gather's transpose is psum_scatter, so this
                    # layer's grad comes back reduce-scattered shard-local
                    # (Megatron-sharded leaves reassemble only their own
                    # tp-local slice; their grads stay model-local too).
                    # compress="bf16" runs the gather at half width; its
                    # transpose then reduce-scatters the grads in bf16 too
                    # (FSDP's collectives ARE its bandwidth cost), while
                    # the stored master params and moments stay f32.
                    # compress="int8" quarters the wire both ways:
                    # forward = ONE quantization per shard (int8 payload +
                    # a per-shard f32 scale on a second all_gather — no
                    # per-hop requantization: all_gather forwards original
                    # payloads); backward = the explicit int8 ring
                    # reduce-scatter (per-hop scales, custom transpose).
                    flat = s.reshape(-1)
                    if compress == "bf16":
                        flat = flat.astype(jnp.bfloat16)
                    if compress == "int8":
                        full = int8_gather(flat)
                    else:
                        full = lax.all_gather(flat, g_axes, tiled=True)
                    if compress == "bf16":
                        full = full.astype(s.dtype)
                    size = int(np.prod(shape[1:]))
                    return full[:size].reshape(shape[1:])

                if prefetch and remat == "params":
                    # Prefetch x regather remat (VERDICT r3 #5, closing the
                    # old exclusion): the trunk UNROLLS — without a loop
                    # boundary the latency-hiding scheduler is free to run
                    # layer k+1's forward gather behind layer k's matmuls
                    # AND layer k-1's backward RE-gather behind layer k's
                    # backward matmuls (the regathers already run twice
                    # under remat='params'; hiding the second copy is pure
                    # win). Each layer keeps its own
                    # jax.checkpoint(dots_saveable), so the residual
                    # profile is exactly scan-mode remat='params': matmul
                    # outputs saved, gathered params + cheap elementwise
                    # recomputed. Cost: n_layers copies of the layer in the
                    # program (compile time), fine at trunk depths that fit
                    # one chip.
                    trunk = p["trunk"]
                    n_l = jax.tree.leaves(trunk)[0].shape[0]

                    def one_layer(hh, layer_shards):
                        layer_p = jax.tree.map(
                            gather_leaf, layer_shards, trunk_shapes
                        )
                        return block_apply({"params": layer_p}, hh)

                    layer_fn = jax.checkpoint(
                        one_layer,
                        policy=jax.checkpoint_policies.dots_saveable,
                    )
                    for i in range(n_l):
                        h = layer_fn(
                            h, jax.tree.map(lambda s: s[i], trunk)
                        )
                elif prefetch:
                    # Software-pipelined parameter prefetch (the FSDP form
                    # of SURVEY §8.4 overlap): iteration k issues layer
                    # k+1's all_gather BEFORE computing layer k, and the
                    # two have no data dependence — the latency-hiding
                    # scheduler can run next layer's gather behind this
                    # layer's compute. A plain scan-over-xs serializes them
                    # (a layer's gather can only start in its own
                    # iteration). Same math; the trade is the gathered
                    # layer riding the scan carry (hence the full-remat
                    # guard in __init__). The scan covers n_l - 1
                    # iterations and the last layer applies AFTER it, so no
                    # iteration gathers a layer it then discards.
                    trunk = p["trunk"]
                    n_l = jax.tree.leaves(trunk)[0].shape[0]

                    def gather_layer(i):
                        return jax.tree.map(
                            lambda s, shape: gather_leaf(
                                lax.dynamic_index_in_dim(
                                    s, i, 0, keepdims=False
                                ),
                                shape,
                            ),
                            trunk,
                            trunk_shapes,
                        )

                    def body(carry, i):
                        hh, cur = carry
                        nxt = gather_layer(i + 1)
                        hh = block_apply({"params": cur}, hh)
                        return (hh, nxt), None

                    (h, last), _ = lax.scan(
                        body, (h, gather_layer(0)), jnp.arange(n_l - 1)
                    )
                    h = block_apply({"params": last}, h)
                else:

                    def body(carry, layer_shards):
                        layer_p = jax.tree.map(
                            gather_leaf, layer_shards, trunk_shapes
                        )
                        return block_apply({"params": layer_p}, carry), None

                    if remat == "full":
                        body_fn = jax.checkpoint(body)
                    elif remat == "params":
                        # drop the gathered full layers from the residuals
                        # and re-gather them on backward. Mechanism: an
                        # ALLOWLIST policy (dots_saveable) — matmul outputs
                        # (the layer's real activations) are saved, while
                        # the gather chain (all_gather + reshapes, not
                        # dots) is recomputed, i.e. the collective runs
                        # twice. A blocklist policy
                        # (save_anything_except_these_names) cannot express
                        # this: the un-named twin the producing eqn emits
                        # is itself saveable, so partial-eval just saves
                        # that same-size copy and the regather buys
                        # nothing (measured: temp bytes identical to
                        # no-remat). Cheap elementwise chains (gelu,
                        # layernorm) recompute alongside — that is
                        # dots_saveable's standard trade.
                        body_fn = jax.checkpoint(
                            body,
                            policy=jax.checkpoint_policies.dots_saveable,
                        )
                    else:
                        body_fn = body
                    h, _ = lax.scan(body_fn, h, p["trunk"])
                logits = head_apply({"params": p["head"]}, h)
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                )
                return ce.sum() * v / denom

            # EXPLICIT psums for the replicated (embed/head) leaves:
            # localize_tree makes them device-varying so their grads stay
            # LOCAL, then grouped_tree_psum reduces them over the mesh —
            # shard_map's automatic transpose-psum for replicated params
            # DOES NOT RUN under check_vma=False (the int8/flash-relax
            # configs silently trained on per-device local embed/head
            # grads until the runtime replica assert caught it —
            # tests/test_vma_replication.py, VERDICT r4 #6). Trunk leaves
            # shard over every axis: localize and the grouped psum are
            # no-ops for them (their reduction IS the gather transpose).
            from akka_allreduce_tpu.comm.allreduce import (
                grouped_tree_psum,
                localize_tree,
            )

            params_in = localize_tree(params, param_specs, axes)
            loss, grads = jax.value_and_grad(masked_loss)(params_in)
            grads = grouped_tree_psum(grads, param_specs, axes)
            loss_avg = lax.psum(loss, axes)  # masked, already /denom
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt, loss_avg, contributors

        data_spec = batch_spec
        from akka_allreduce_tpu.ops.local_attention import flash_vma_relax

        # with sp == 1 (or Ulysses) the blocks run FULL local attention, so
        # the flash kernel can dispatch; its outputs carry no varying-axes
        # annotation (same check_vma gate as LongContext/MoE/Pipeline)
        # int8's ring ppermute loop erases varying-axes typing (the same
        # relaxation every int8 trainer path needs)
        self._check_vma = (
            not flash_vma_relax(
                seq_len, d_model // n_heads, sp=self.sp, seq_impl=seq_impl
            )
            and compress != "int8"
        )
        self._step = jax.jit(
            jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(
                    self._param_specs,
                    self._opt_specs,
                    data_spec,
                    data_spec,
                    P(data_axis),
                ),
                out_specs=(self._param_specs, self._opt_specs, P(), P()),
                check_vma=self._check_vma,
            ),
            donate_argnums=(0, 1),
        )
        self._raw_step = step  # reused by train_chain's on-device loop
        self._chains: dict = {}

    def _place(self, tree, specs):
        """device_put every leaf onto its PartitionSpec over this mesh."""
        return jax.device_put(
            tree,
            jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                specs,
                is_leaf=lambda s: isinstance(s, P),
            ),
        )

    # -- stepping ------------------------------------------------------------

    def _place_batch_tokens(self, tokens, labels):
        return place_tokens(
            tokens, labels, self._data_sharding,
            seq_len=self.seq_len, dp=self.dp,
        )

    def train_step(
        self,
        tokens: np.ndarray,
        labels: np.ndarray,
        valid: Sequence[float] | None = None,
    ) -> TrainStepMetrics:
        """One step on a GLOBAL (batch, seq_len) token array; ``valid`` is
        the per-DP-replica-row contributor mask, shape (dp,)."""
        valid_arr = normalize_valid(valid, self.dp)
        xd, yd = self._place_batch_tokens(tokens, labels)
        vd = place_mask(valid_arr, self._valid_sharding)
        self.params, self.opt_state, loss, cnt = self._step(
            self.params, self.opt_state, xd, yd, vd
        )
        self.step_num += 1
        return TrainStepMetrics(
            step=self.step_num, loss=float(loss), contributors=float(cnt)
        )

    # -- on-device training chain (no host I/O per step) ---------------------

    def _build_chain(self, sampler, steps: int, rows_per_replica: int):
        raw_step = self._raw_step
        data_axis, seq_axis = self.data_axis, self.seq_axis
        t_local = self.seq_len // self.sp

        def chain(params, opt_state, key, valid):
            # one stream per DP replica ROW: model/seq shards of a row fold
            # the same data coordinate so they agree on its tokens; seq
            # shards slice their own T_local columns (the LongContext
            # chain's discipline)
            rkey = jax.random.fold_in(key, lax.axis_index(data_axis))
            s = lax.axis_index(seq_axis) if seq_axis is not None else None

            def body(carry, i):
                p, o = carry
                k = jax.random.fold_in(rkey, i)
                x, y = sampler(k, rows_per_replica)
                if s is not None:
                    x = lax.dynamic_slice_in_dim(
                        x, s * t_local, t_local, axis=1
                    )
                    y = lax.dynamic_slice_in_dim(
                        y, s * t_local, t_local, axis=1
                    )
                p, o, loss, cnt = raw_step(p, o, x, y, valid)
                return (p, o), (loss, cnt)

            (params, opt_state), (losses, cnts) = lax.scan(
                body, (params, opt_state), jnp.arange(steps)
            )
            return params, opt_state, losses, cnts

        mapped = jax.shard_map(
            chain,
            mesh=self.mesh,
            in_specs=(
                self._param_specs,
                self._opt_specs,
                P(),
                P(self.data_axis),
            ),
            out_specs=(self._param_specs, self._opt_specs, P(), P()),
            check_vma=self._check_vma,
        )
        return jax.jit(mapped, donate_argnums=(0, 1))

    def train_chain(
        self,
        sampler,
        steps: int,
        rows_per_replica: int,
        *,
        valid: Sequence[float] | None = None,
        seed: int = 0,
    ) -> list[TrainStepMetrics]:
        """Run ``steps`` FSDP steps entirely on device in ONE dispatch.

        ``sampler`` is a traced ``(key, rows) -> (tokens, labels)``
        producing GLOBAL (rows, seq_len) sequences
        (``SyntheticCopyLM.device_sampler``)."""
        from akka_allreduce_tpu.train.trainer import run_chain_cached

        losses, cnts = run_chain_cached(
            self,
            sampler,
            steps,
            rows_per_replica,
            lambda: self._build_chain(sampler, steps, rows_per_replica),
            valid,
            self.dp,
            self._valid_sharding,
            seed,
        )
        out = []
        for loss, cnt in zip(losses, cnts):
            self.step_num += 1
            out.append(
                TrainStepMetrics(
                    step=self.step_num,
                    loss=float(loss),
                    contributors=float(cnt),
                )
            )
        return out

    # -- gathered views (tests / checkpoint seam) ----------------------------

    def gathered_params(self) -> dict:
        """Full (unsharded) params pytree on the host — checkpoint scale."""
        return self.checkpoint_state()["params"]

    @property
    def trunk_shard_elems(self) -> int:
        """Per-device element count of the sharded trunk (layers x per-shard
        slice — the last dim — for both the 3D and the TP 4D layout)."""
        return int(
            sum(
                l.shape[0] * l.shape[-1]
                for l in jax.tree.leaves(self.params["trunk"])
            )
        )

    # -- checkpoint seam (mesh-size-independent, the ZeRO-1 discipline) ------

    @staticmethod
    def _is_params_container(t) -> bool:
        """A dict mirroring the params layout (optax moments do) — its
        trunk subtree holds the FSDP-sharded leaves."""
        return isinstance(t, dict) and "trunk" in t

    def checkpoint_capture(self) -> dict:
        """Shard-local device state for the async checkpoint path: each
        leaf is 1/(dp·sp[·tp]) of the trunk, already on device. The async
        checkpointer copies these HBM-to-HBM and drains them to host in the
        background — no gather, no step-loop stall (VERDICT r4 #1);
        :meth:`checkpoint_assemble` unshards on the writer thread."""
        return {"params": self.params, "opt_state": self.opt_state}

    def checkpoint_assemble(self, host: dict) -> dict:
        """Pure-host (numpy) unshard of a captured tree into the
        mesh-size-independent serialized form. Runs on the checkpoint
        writer thread — must not touch a device."""

        def unshard_leaf(s, shape, tp_dim):
            s = np.asarray(s)
            if tp_dim < 0:
                return np.asarray(_unshard_leaf(s, shape))
            return np.asarray(_unshard_leaf_tp(s, shape, tp_dim))

        def unshard_trunk(container):
            out = dict(container)
            out["trunk"] = jax.tree.map(
                unshard_leaf,
                container["trunk"],
                self._trunk_shapes,
                self._trunk_tp_dims,
            )
            return out

        params = unshard_trunk(host["params"])
        opt_state = jax.tree.map(
            lambda t: unshard_trunk(t) if self._is_params_container(t) else t,
            host["opt_state"],
            is_leaf=self._is_params_container,
        )
        return {"params": params, "opt_state": opt_state}

    def checkpoint_state(self) -> dict:
        """Mesh-size-independent: trunk leaves (params AND optimizer
        moments) gather to their full shapes on the host (the ZeRO-1
        gather-then-reshard discipline). Synchronous — the async
        checkpointer uses capture/assemble directly."""
        host = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), self.checkpoint_capture()
        )
        return self.checkpoint_assemble(host)

    def checkpoint_template(self) -> dict:
        """Abstract (ShapeDtypeStruct-only) twin of :meth:`checkpoint_state`
        for the restore target: without it, TrainerCheckpointer.restore
        would gather the throwaway freshly-initialized full trunk AND both
        adam moments to host just to learn the shapes (ADVICE r2)."""

        def tmpl_container(container):
            out = {
                k: jax.tree.map(
                    lambda l: jax.ShapeDtypeStruct(
                        jnp.shape(l), jnp.asarray(l).dtype
                    ),
                    v,
                )
                for k, v in container.items()
                if k != "trunk"
            }
            out["trunk"] = jax.tree.map(
                lambda s, shape: jax.ShapeDtypeStruct(shape, s.dtype),
                container["trunk"],
                self._trunk_shapes,
            )
            return out

        opt_state = jax.tree.map(
            lambda t: (
                tmpl_container(t)
                if self._is_params_container(t)
                else jax.ShapeDtypeStruct(jnp.shape(t), jnp.asarray(t).dtype)
            ),
            self.opt_state,
            is_leaf=self._is_params_container,
        )
        return {
            "params": tmpl_container(self.params),
            "opt_state": opt_state,
        }

    def _reshard_trunk(self, container: dict) -> dict:
        """FULL (unsharded) trunk leaves -> this mesh's 1/(dp·sp[·tp])
        storage shards — the mesh-size-independent restore step, shared by
        checkpoint restore and the flat-params deposit seam."""
        n = self.dp * self.sp

        def reshard_leaf(full, tp_dim):
            full = jnp.asarray(full)
            if tp_dim < 0 or self.tp == 1:
                return _shard_leaf(full, n)
            return _shard_leaf_tp(full, n, self.tp, tp_dim)

        out = dict(container)
        out["trunk"] = jax.tree.map(
            reshard_leaf, container["trunk"], self._trunk_tp_dims
        )
        return out

    def restore_checkpoint_state(self, state: dict) -> None:
        # checkpoints carry FULL (unsharded) trunk leaves, so restore
        # reshards for THIS mesh's geometry — any (dp, sp, tp) combination
        self.params = self._place(
            self._reshard_trunk(state["params"]), self._param_specs
        )
        opt_state = jax.tree.map(
            lambda t: (
                self._reshard_trunk(t) if self._is_params_container(t) else t
            ),
            state["opt_state"],
            is_leaf=self._is_params_container,
        )
        self.opt_state = self._place(opt_state, self._opt_specs)

    # -- weights as a flat buffer (binder deposit seam) ----------------------

    def get_flat_params(self) -> np.ndarray:
        from akka_allreduce_tpu.binder.api import flatten_pytree

        return flatten_pytree(self.gathered_params())[0]

    def set_flat_params(self, vec: np.ndarray) -> None:
        """Inverse of :meth:`get_flat_params`: a flat vector of the FULL
        (unsharded) params unflattens and re-shards 1/(dp·sp[·tp]) onto
        the current mesh. Optimizer state is untouched (the
        elastic-averaging pull adjusts weights only)."""
        from jax.flatten_util import ravel_pytree

        full = self.gathered_params()
        flat, unravel = ravel_pytree(full)
        if vec.shape != flat.shape:
            raise ValueError(
                f"expected flat params of shape {flat.shape}, got {vec.shape}"
            )
        new_full = unravel(jnp.asarray(vec, jnp.float32))
        self.params = self._place(
            self._reshard_trunk(new_full), self._param_specs
        )
