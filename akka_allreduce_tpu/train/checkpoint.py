"""Checkpoint/resume for the DP trainer (SURVEY.md §6 "Checkpoint / resume").

The reference keeps no checkpoint state of its own — allreduce rounds are
stateless beyond the round window, and model save/load lives in its BIDMach
dependency. For capability parity of "resume after dropout" (BASELINE.json
config 5) the TPU build provides the trainer-layer equivalent: Orbax
checkpoints of ``{params, opt_state, step}``, plus a zero-copy in-memory
snapshot used by the elastic re-mesh path (SURVEY.md §8.4 — "checkpoint-in-HBM
→ reinit mesh → resume").
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P


def state_shardings(trainer) -> tuple[Any, Any]:
    """(param_shardings, opt_shardings) for placing restored state.

    Sharded trainers (TP / EP / PP — anything exposing ``_param_specs`` /
    ``_opt_specs`` PartitionSpec trees) get per-leaf NamedShardings over
    their CURRENT mesh; plain DP trainers fall back to the replicated
    sharding. Either way, restore works across a re-mesh: leaves are placed
    fresh onto whatever mesh the trainer has now.
    """
    mesh = getattr(trainer, "mesh", None)
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    def tree_of(specs):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs, is_leaf=is_spec
        )

    pspecs = getattr(trainer, "_param_specs", None)
    ospecs = getattr(trainer, "_opt_specs", None)
    p_sh = (
        tree_of(pspecs)
        if mesh is not None and pspecs is not None
        else trainer._replicated
    )
    o_sh = (
        tree_of(ospecs)
        if mesh is not None and ospecs is not None
        else trainer._replicated
    )
    return p_sh, o_sh


def place_on(tree, sharding) -> Any:
    """Device-put every array leaf of ``tree`` onto ``sharding`` (a single
    sharding for all leaves, or a matching tree of per-leaf shardings).

    jax.Array leaves reshard on device (a no-op when already placed — the
    Orbax restore target usually carries the right sharding, so no
    full-model host round trip); numpy leaves (Snapshot data) upload.
    """

    def put(x, s):
        if isinstance(x, (jax.Array, np.ndarray)):
            return jax.device_put(x, s)
        return x

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree.map(lambda x: put(x, sharding), tree)
    return jax.tree.map(put, tree, sharding)


def _restore_ef(trainer, ef) -> None:
    """Place a restored error-feedback residual, redistributing across a
    re-mesh: the residual is per-device withheld gradient mass, so when the
    device count changed we preserve its SUM (what the collective is still
    owed) by splitting it evenly over the new devices."""
    ef = np.asarray(ef, np.float32)
    n = trainer.n_devices
    if ef.shape[0] != n:
        ef = np.tile(ef.sum(axis=0, keepdims=True) / n, (n, 1))
    trainer._ef = jax.device_put(ef, trainer._data_sharding)


def capture_state(trainer) -> tuple[dict, bool]:
    """``(state, custom)``: the serializable state tree of ``trainer`` under
    either checkpoint protocol — the ONE place that knows what trainer
    state consists of. ``custom=True`` means the tree came from the
    trainer-defined ``checkpoint_state()`` (ZeRO-1 / FSDP / Pipeline, host
    numpy, mesh-size-independent); otherwise it is the live
    ``{params, opt_state[, ef]}`` device pytree. Every checkpoint path
    (sync / async / delta / Snapshot) captures through here, so a new
    piece of trainer state is added exactly once."""
    if hasattr(trainer, "checkpoint_state"):
        return dict(trainer.checkpoint_state()), True
    state = {"params": trainer.params, "opt_state": trainer.opt_state}
    if getattr(trainer, "_ef", None) is not None:
        # error-feedback residual is training state: dropping it on
        # restart would permanently lose every withheld gradient
        state["ef"] = trainer._ef
    return state, False


_POD_MESH_MSG = (
    "DeltaCheckpointer is a per-host store; state sharded over a mesh "
    "that spans OS processes cannot be host-gathered here — use "
    "TrainerCheckpointer (Orbax coordinates cross-process saves) on pod "
    "meshes"
)


def _fully_addressable(tree) -> bool:
    """True when every jax.Array leaf is visible to THIS process — the
    precondition for host-side capture without Orbax's cross-process
    coordination."""
    return all(
        x.is_fully_addressable
        for x in jax.tree.leaves(tree)
        if isinstance(x, jax.Array)
    )


def _copy_tree_async(tree):
    """Donation-proof on-device copy with device-to-host transfers
    launched: new buffers with the same shardings, so the training loop's
    donated originals can die while the copy's transfer is still in
    flight. The background writer's ``np.asarray`` then merely joins the
    transfer instead of starting it."""
    import jax.numpy as jnp

    def copy_leaf(x):
        if isinstance(x, jax.Array):
            y = jnp.copy(x)
            y.copy_to_host_async()
            return y
        return x

    return jax.tree.map(copy_leaf, tree)


def async_capture(trainer):
    """``(captured, assemble, custom)`` for the non-stalling checkpoint
    paths, or ``None`` when the state is not fully addressable from this
    process (pod meshes — the Orbax caller falls back to its
    multihost-aware synchronous save; the per-host delta store raises).

    Trainers exposing the shard-local protocol
    (``checkpoint_capture``/``checkpoint_assemble`` — ZeRO-1, FSDP,
    Pipeline) capture as on-device copies of their OWN shards, no gather
    (VERDICT r4 #1); ``assemble`` converts the host tree into the
    serialized form on the writer thread. Pytree-state trainers capture
    ``{params, opt_state[, ef]}`` the same way with ``assemble=None``
    (the host tree IS the serialized form). Custom-protocol trainers
    WITHOUT the shard-local seam pay a synchronous ``checkpoint_state()``
    gather here and hand the host tree to the writer. ``custom`` mirrors
    :func:`capture_state`'s flag (the delta manifest records it)."""
    if hasattr(trainer, "checkpoint_capture"):
        live = dict(trainer.checkpoint_capture())
        if not _fully_addressable(live):
            return None
        return _copy_tree_async(live), trainer.checkpoint_assemble, True
    state, custom = capture_state(trainer)
    if custom:
        # the gather inside checkpoint_state was the synchronous part;
        # the tree is already host numpy
        return state, None, True
    if not _fully_addressable(state):
        return None
    return _copy_tree_async(state), None, False


class _BackgroundWriter:
    """One-save-in-flight background machinery shared by the async
    checkpointers. Subclasses call :meth:`_writer_init` in ``__init__``
    and :meth:`_launch` with the write closure; a background failure is
    re-raised on the next ``busy``/``save``/``restore``/``close``."""

    def _writer_init(self) -> None:
        import threading

        self._lock = threading.Lock()  # serializes store access
        self._inflight: "threading.Thread | None" = None
        self._errors: list = []

    def _launch(self, write, name: str) -> None:
        import threading

        def guarded():
            try:
                write()
            except Exception as e:  # surfaced on the next save/drain
                self._errors.append(e)

        t = threading.Thread(target=guarded, name=name, daemon=True)
        self._inflight = t
        t.start()

    def _drain(self) -> None:
        t = self._inflight
        if t is not None:
            t.join()
            self._inflight = None
        if self._errors:
            err = self._errors[:]
            self._errors.clear()
            raise RuntimeError(f"background checkpoint save failed: {err[0]}")

    def busy(self) -> bool:
        t = self._inflight
        if t is not None and not t.is_alive():
            self._drain()  # reap + surface any background error
        return self._inflight is not None

    def wait_until_finished(self) -> None:
        """Block until the in-flight save (if any) is durable; re-raise a
        background failure."""
        self._drain()


@dataclasses.dataclass
class Snapshot:
    """In-memory (host RAM) snapshot of trainer state for fast re-mesh resume.

    Held as numpy so it survives the death of the device mesh it came from:
    during elastic reconfiguration the old mesh's devices may be gone by the
    time we restore.

    Trainers with the trainer-defined checkpoint protocol
    (``checkpoint_state``/``restore_checkpoint_state`` — ZeRO-1, FSDP)
    snapshot through it: their serialized form is mesh-size-INDEPENDENT, so
    the same snapshot restores onto a mesh with a different device count —
    exactly what the elastic re-mesh needs (VERDICT r3 #3). Pytree-state
    trainers use the params/opt_state capture as before.
    """

    params: Any  # pytree of np.ndarray (pytree-state trainers)
    opt_state: Any  # pytree of np.ndarray / leaves
    step: int
    ef: Any = None  # error-feedback residual (n_devices, params) or None
    custom: dict | None = None  # trainer-defined checkpoint_state() payload

    @classmethod
    def capture(cls, trainer) -> "Snapshot":
        state, custom = capture_state(trainer)
        host = jax.tree.map(np.asarray, state)
        if custom:
            return cls(
                params=None,
                opt_state=None,
                step=trainer.step_num,
                custom=host,
            )
        return cls(
            params=host["params"],
            opt_state=host["opt_state"],
            step=trainer.step_num,
            ef=host.get("ef"),
        )

    def restore_into(self, trainer) -> None:
        """Place this snapshot into ``trainer``, honoring its sharding layout
        (replicated for plain DP; per-leaf specs for TP/EP/PP trainers;
        the trainer-defined reshard for ZeRO-1/FSDP)."""
        if self.custom is not None:
            if not hasattr(trainer, "restore_checkpoint_state"):
                raise TypeError(
                    "snapshot was captured through a trainer-defined "
                    "checkpoint protocol; the restore target has none"
                )
            # restore may mutate the dict (zero1 pops format_version) and
            # the snapshot may be restored more than once — hand over a
            # shallow copy
            trainer.restore_checkpoint_state(dict(self.custom))
            trainer.step_num = self.step
            return
        p_sh, o_sh = state_shardings(trainer)
        trainer.params = place_on(self.params, p_sh)
        trainer.opt_state = place_on(self.opt_state, o_sh)
        trainer.step_num = self.step
        if getattr(trainer, "_ef", None) is not None:
            if self.ef is not None:
                _restore_ef(trainer, self.ef)
            else:
                # snapshot carries no residual: a stale live one would
                # re-inject the PRE-restore trajectory's withheld mass
                # (ADVICE r4) — zero it so restore fully determines state
                _restore_ef(
                    trainer, np.zeros(trainer._ef.shape, np.float32)
                )


class TrainerCheckpointer:
    """Durable on-disk checkpoints of trainer state via Orbax.

    Usage::

        ckpt = TrainerCheckpointer(dir)
        ckpt.save(trainer)                  # every k steps
        step = ckpt.restore(trainer)        # after restart / re-mesh
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3) -> None:
        self.directory = Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, trainer, *, force: bool = False, block: bool = True) -> bool:
        # ``block`` exists for signature parity with the async subclass —
        # this save is synchronous regardless
        if trainer.step_num in self._mgr.all_steps():
            return False  # this step is already durable; nothing to do
        state, _ = capture_state(trainer)
        state["step"] = trainer.step_num
        saved = self._mgr.save(
            trainer.step_num, args=ocp.args.StandardSave(state), force=force
        )
        self._mgr.wait_until_finished()
        return bool(saved)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def _saved_top_keys(self, step: int) -> set | None:
        """Top-level keys of the saved tree (None if metadata unavailable).
        Lets restore reconcile OPTIONAL template keys with what the
        checkpoint actually carries (ADVICE r2: an EF/non-EF or version-key
        difference must not surface as a generic Orbax tree mismatch)."""
        try:
            return set(self._mgr.item_metadata(step).keys())
        except Exception:
            return None

    def restore(self, trainer, step: int | None = None) -> int:
        """Restore trainer state in place; returns the restored step number."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        if hasattr(trainer, "checkpoint_state"):
            # prefer the abstract template (shape/dtype only): building the
            # target must not gather the throwaway fresh state to host
            template_fn = getattr(
                trainer, "checkpoint_template", trainer.checkpoint_state
            )
            target = dict(template_fn())
            target["step"] = trainer.step_num
            saved = self._saved_top_keys(step)
            optional = getattr(trainer, "checkpoint_optional_keys", frozenset())
            if saved is not None:
                for k in optional:
                    # keys newer builds always write (format_version, the
                    # always-present ef_sum) may be absent from older
                    # checkpoints; drop them from the target rather than
                    # fail the whole restore on tree structure
                    if k in target and k not in saved:
                        target.pop(k)
            try:
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target)
                )
            except Exception as e:
                if (
                    "format_version" in optional
                    and saved is not None
                    and "format_version" not in saved
                ):
                    raise ValueError(
                        f"checkpoint step {step} under {self.directory} "
                        "predates this trainer's serialized format (no "
                        "format_version key — e.g. the round-1 padded "
                        "per-mesh ZeRO-1 layout) and cannot be loaded; "
                        "re-save it from the build that wrote it"
                    ) from e
                raise
            trainer.step_num = int(restored.pop("step"))
            trainer.restore_checkpoint_state(restored)
            return trainer.step_num
        # Use the trainer's live state as the abstract target so leaves come
        # back with the right dtypes/shardings for its current mesh.
        target = {
            "params": trainer.params,
            "opt_state": trainer.opt_state,
            "step": trainer.step_num,
        }
        has_ef = getattr(trainer, "_ef", None) is not None
        if has_ef:
            target["ef"] = trainer._ef
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        # Orbax may hand back single-device arrays; re-place onto the
        # trainer's CURRENT layout — replicated for plain DP, per-leaf
        # shardings for TP/EP/PP trainers (this is also what makes
        # restore-into-a-different-mesh work after an elastic re-mesh).
        p_sh, o_sh = state_shardings(trainer)
        trainer.params = place_on(restored["params"], p_sh)
        trainer.opt_state = place_on(restored["opt_state"], o_sh)
        trainer.step_num = int(restored["step"])
        if has_ef and "ef" in restored:
            _restore_ef(trainer, restored["ef"])
        return trainer.step_num

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "TrainerCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DeltaCheckpointer:
    """Per-leaf, content-addressed checkpoints: a save writes only the
    leaves whose bytes CHANGED since any kept checkpoint (VERDICT r3
    next-round #2, "optional per-leaf delta saves" — size checkpoints to
    the link).

    Layout: ``blobs/<sha256>.npy`` holds each distinct leaf content once;
    ``manifest_<step>.json`` maps leaf paths to blob hashes. Unchanged
    leaves (frozen embeddings, converged moments, a quiet EF residual, the
    weights themselves when saving more often than they change) cost one
    hash, zero bytes on the wire/disk. Pruning drops manifests beyond
    ``max_to_keep`` and any blob no kept manifest references.

    Works with both state protocols (the params/opt_state pytree and the
    trainer-defined ``checkpoint_state``); restore places leaves through
    the same machinery as :class:`TrainerCheckpointer`.
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3) -> None:
        if max_to_keep < 1:
            # sorted(manifests)[:-0] would be an empty slice — pruning
            # silently off and the store growing unboundedly (ADVICE r4)
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.directory = Path(directory).absolute()
        self.blobs = self.directory / "blobs"
        self.blobs.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep

    # -- tree <-> {path: leaf} -----------------------------------------------

    @staticmethod
    def _flatten(state) -> dict:
        import jax.tree_util as jtu

        return {
            jtu.keystr(path): leaf
            for path, leaf in jtu.tree_leaves_with_path(state)
        }

    def _capture(self, trainer) -> tuple[dict, bool]:
        state, custom = capture_state(trainer)
        if not _fully_addressable(state):
            raise NotImplementedError(_POD_MESH_MSG)
        return jax.tree.map(np.asarray, state), custom

    # -- save ----------------------------------------------------------------

    def _manifests(self) -> dict[int, Path]:
        out = {}
        for f in self.directory.glob("manifest_*.json"):
            try:
                out[int(f.stem.split("_", 1)[1])] = f
            except ValueError:
                continue
        return out

    def latest_step(self) -> int | None:
        steps = self._manifests()
        return max(steps) if steps else None

    def save(self, trainer, *, force: bool = False, block: bool = True) -> dict:
        """Write a delta checkpoint; returns ``{written_bytes,
        reused_bytes, written_leaves, reused_leaves}`` so callers can see
        the delta actually saving bytes. ``force``/``block`` exist for
        signature parity with the Orbax checkpointers (this save is
        synchronous — :class:`AsyncDeltaCheckpointer` moves the hash/write
        off-thread — and never step-deduped: an identical re-save just
        reuses every blob)."""
        host, custom = self._capture(trainer)
        return self._write_delta(host, custom, int(trainer.step_num))

    def _write_delta(self, host: dict, custom: bool, step: int) -> dict:
        """Hash every leaf, write the new blobs + manifest, prune. Pure
        host-side work on an already-host tree — the half a background
        writer thread can run.

        Durability order matters: every blob is fsynced before its atomic
        rename, and the manifest is fsynced before ITS rename — a crash
        mid-save must leave the old manifests intact and can never publish
        a manifest that names truncated chunk files (the page-cache-loss
        corruption class; regression-pinned in tests/test_checkpoint.py)."""
        import json
        import os as _os

        from akka_allreduce_tpu.control.statetransfer import (
            fsync_write,
            leaf_sha,
            publish_file,
        )

        flat = self._flatten(host)
        manifest = {
            "step": step,
            "custom": custom,
            "leaves": {},
        }
        stats = dict(
            written_bytes=0, reused_bytes=0, written_leaves=0, reused_leaves=0
        )
        for key, leaf in flat.items():
            arr = np.asarray(leaf)
            # ONE definition of the content hash (statetransfer.leaf_sha):
            # the peer chunk transfer verifies fetched blobs against these
            # names, so the hash here and the verifier must never diverge
            sha = leaf_sha(arr)
            blob = self.blobs / f"{sha}.npy"
            if blob.exists():
                stats["reused_bytes"] += arr.nbytes
                stats["reused_leaves"] += 1
            else:
                tmp = blob.with_suffix(".tmp")
                with open(tmp, "wb") as f:  # np.save(path) appends .npy
                    np.save(f, arr, allow_pickle=False)
                    f.flush()
                    _os.fsync(f.fileno())
                publish_file(tmp, blob)  # atomic + directory fsync
                stats["written_bytes"] += arr.nbytes
                stats["written_leaves"] += 1
            manifest["leaves"][key] = sha
        tmp = self.directory / f".manifest_{step}.tmp"
        # fsync BEFORE the atomic rename (statetransfer.fsync_write — one
        # definition of the durability recipe): a crash mid-save leaves old
        # manifests + maybe some orphan blobs, never a torn manifest or one
        # whose blobs' bytes were still in the page cache
        fsync_write(tmp, json.dumps(manifest).encode())
        publish_file(tmp, self.directory / f"manifest_{step}.json")
        self._prune()
        return stats

    def _prune(self) -> None:
        import json

        manifests = self._manifests()
        for step in sorted(manifests)[: -self.max_to_keep]:
            manifests.pop(step).unlink()
        live = set()
        for f in manifests.values():
            live.update(json.loads(f.read_text())["leaves"].values())
        for blob in self.blobs.glob("*.npy"):
            if blob.stem not in live:
                blob.unlink()
        # a crash between tmp write and atomic publish leaves orphans a
        # normal prune would never reclaim — sweep them here (the current
        # save has already published its blobs by the time prune runs)
        for stale in self.blobs.glob("*.tmp"):
            stale.unlink()
        for stale in self.directory.glob(".manifest_*.tmp"):
            stale.unlink()

    # -- restore -------------------------------------------------------------

    def restore(self, trainer, step: int | None = None) -> int:
        import json

        import jax.tree_util as jtu

        manifests = self._manifests()
        step = max(manifests) if step is None and manifests else step
        if step is None or step not in manifests:
            raise FileNotFoundError(
                f"no delta checkpoint for step {step} under {self.directory}"
            )
        manifest = json.loads(manifests[step].read_text())
        leaves = manifest["leaves"]

        def load(path, _template):
            key = jtu.keystr(path)
            if key not in leaves:
                raise KeyError(
                    f"checkpoint at step {step} has no leaf {key!r} (trainer "
                    "structure mismatch)"
                )
            return np.load(self.blobs / f"{leaves[key]}.npy", allow_pickle=False)

        if manifest["custom"]:
            if not hasattr(trainer, "restore_checkpoint_state"):
                raise TypeError(
                    "delta checkpoint was captured through a trainer-defined "
                    "checkpoint protocol; the restore target has none"
                )
            template_fn = getattr(
                trainer, "checkpoint_template", trainer.checkpoint_state
            )
            state = jtu.tree_map_with_path(load, dict(template_fn()))
            trainer.restore_checkpoint_state(state)
        else:
            target = {"params": trainer.params, "opt_state": trainer.opt_state}
            has_ef = getattr(trainer, "_ef", None) is not None
            if has_ef and any(k.startswith("['ef']") for k in leaves):
                target["ef"] = trainer._ef
            state = jtu.tree_map_with_path(load, target)
            p_sh, o_sh = state_shardings(trainer)
            trainer.params = place_on(state["params"], p_sh)
            trainer.opt_state = place_on(state["opt_state"], o_sh)
            if "ef" in state:
                _restore_ef(trainer, state["ef"])
            elif has_ef:
                # the checkpoint carries no residual: keeping the live
                # (possibly nonzero, stale) one would make post-restore
                # state not purely the saved state (ADVICE r4) — zero it
                _restore_ef(trainer, np.zeros(trainer._ef.shape, np.float32))
        trainer.step_num = int(manifest["step"])
        return trainer.step_num

    def close(self) -> None:
        """Nothing to flush (saves are synchronous); CLI-loop parity."""

    def __enter__(self) -> "DeltaCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncTrainerCheckpointer(_BackgroundWriter, TrainerCheckpointer):
    """Checkpoints that do not stall the step loop (VERDICT r3 next-round
    #2: "checkpoint cost is part of the recovery story").

    ``save`` splits into a cheap capture phase in the step gap and a
    background phase off-thread (see :func:`async_capture`):

    - **pytree-state trainers** (DP / MoE / LongContext): capture = ONE
      on-device copy of the state (HBM-to-HBM, microseconds to
      milliseconds) + launching ``copy_to_host_async`` on every leaf.
      The training loop resumes immediately and keeps donating its own
      buffers — the copy is independent — while the device-to-host
      transfer (minutes for the 4.8 GB flagship state over a tunneled
      link) overlaps the subsequent steps. The background thread blocks on
      the transfers and then runs the Orbax write.
    - **trainers with the shard-local protocol** (ZeRO-1, FSDP, Pipeline —
      ``checkpoint_capture``/``checkpoint_assemble``): same on-device copy
      of each shard, NO gather in the capture phase (VERDICT r4 #1); the
      writer thread drains the shards and runs the trainer's pure-host
      ``checkpoint_assemble`` (unshard / unpad / re-order) before the
      Orbax write.

    Crash safety: the background write goes through the same Orbax
    manager, which finalizes each step directory atomically — a crash
    mid-save leaves the previous checkpoint as ``latest_step`` and the
    partial step invisible to restore (tested by SIGKILLing a writer
    mid-save in tests/test_checkpoint.py).

    One save is in flight at a time: a ``save`` while busy returns False
    (callers keep training and retry next interval) unless ``block=True``.
    Restores and ``close`` drain the in-flight save first.
    """

    def __init__(self, directory, *, max_to_keep: int = 3) -> None:
        super().__init__(directory, max_to_keep=max_to_keep)
        self._writer_init()

    def save(self, trainer, *, force: bool = False, block: bool = False) -> bool:
        if self.busy():
            if not block:
                return False
            self._drain()
        with self._lock:
            if trainer.step_num in self._mgr.all_steps():
                return False
        step = trainer.step_num
        cap = async_capture(trainer)
        if cap is None:
            # a mesh spanning OS processes: Orbax's cross-process save
            # coordinates ALL processes, and per-process background threads
            # can disagree on busy-skip (one process skips while another
            # enters the barrier — deadlock). Take the multihost-aware
            # synchronous path instead; async capture stays a
            # single-controller optimization.
            return super().save(trainer, force=force)
        captured, assemble, _ = cap

        def write():
            host = jax.tree.map(
                lambda x: np.asarray(x)
                if isinstance(x, (jax.Array, np.ndarray))
                else x,
                captured,
            )
            state = assemble(host) if assemble is not None else host
            state["step"] = step
            with self._lock:
                self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
                self._mgr.wait_until_finished()

        self._launch(write, f"ckpt-save-{step}")
        if block:
            self._drain()
        return True

    def restore(self, trainer, step: int | None = None) -> int:
        self._drain()  # a restore must see the freshest durable step
        return super().restore(trainer, step)

    def latest_step(self) -> int | None:
        with self._lock:
            return self._mgr.latest_step()

    def close(self) -> None:
        try:
            self._drain()
        finally:
            super().close()


class AsyncDeltaCheckpointer(_BackgroundWriter, DeltaCheckpointer):
    """Delta checkpoints whose hashing and blob writes run off-thread —
    link-sized saves AND non-stalling saves at once (VERDICT r4 #1: the
    round-4 store made them mutually exclusive).

    Capture is the same non-gathering phase as
    :class:`AsyncTrainerCheckpointer` (on-device copies, shard-local for
    the ZeRO-1/FSDP/Pipeline protocol); the writer thread drains, runs the
    trainer's ``checkpoint_assemble``, then hashes leaves and writes only
    the changed blobs. ``save`` returns True when a background save was
    launched (False while one is still in flight); the per-save byte
    stats land in :attr:`last_stats` once it completes (``busy()`` →
    False, or after ``wait_until_finished``). Still a per-host store:
    non-fully-addressable state raises, as in the sync class."""

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3) -> None:
        super().__init__(directory, max_to_keep=max_to_keep)
        self._writer_init()
        #: stats dict of the most recently COMPLETED save (None before any)
        self.last_stats: dict | None = None

    def save(
        self, trainer, *, force: bool = False, block: bool = False
    ) -> bool:
        if self.busy():
            if not block:
                return False
            self._drain()
        step = int(trainer.step_num)
        cap = async_capture(trainer)
        if cap is None:
            raise NotImplementedError(_POD_MESH_MSG)
        captured, assemble, custom = cap

        def write():
            host = jax.tree.map(
                lambda x: np.asarray(x)
                if isinstance(x, (jax.Array, np.ndarray))
                else x,
                captured,
            )
            state = assemble(host) if assemble is not None else host
            with self._lock:
                self.last_stats = self._write_delta(state, custom, step)

        self._launch(write, f"delta-save-{step}")
        if block:
            self._drain()
        return True

    def latest_step(self) -> int | None:
        with self._lock:
            return super().latest_step()

    def restore(self, trainer, step: int | None = None) -> int:
        self._drain()  # a restore must see the freshest durable step
        return super().restore(trainer, step)

    def close(self) -> None:
        try:
            self._drain()
        finally:
            # DeltaCheckpointer.close() is a no-op today, but a drain failure
            # must never skip whatever cleanup it grows (ADVICE r5)
            super().close()
