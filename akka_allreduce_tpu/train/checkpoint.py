"""Checkpoint/resume for the DP trainer (SURVEY.md §6 "Checkpoint / resume").

The reference keeps no checkpoint state of its own — allreduce rounds are
stateless beyond the round window, and model save/load lives in its BIDMach
dependency. For capability parity of "resume after dropout" (BASELINE.json
config 5) the TPU build provides the trainer-layer equivalent: Orbax
checkpoints of ``{params, opt_state, step}``, plus a zero-copy in-memory
snapshot used by the elastic re-mesh path (SURVEY.md §8.4 — "checkpoint-in-HBM
→ reinit mesh → resume").
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


@dataclasses.dataclass
class Snapshot:
    """In-memory (host RAM) snapshot of trainer state for fast re-mesh resume.

    Held as numpy so it survives the death of the device mesh it came from:
    during elastic reconfiguration the old mesh's devices may be gone by the
    time we restore.
    """

    params: Any  # pytree of np.ndarray
    opt_state: Any  # pytree of np.ndarray / leaves
    step: int

    @classmethod
    def capture(cls, trainer) -> "Snapshot":
        host = lambda t: jax.tree.map(lambda x: np.asarray(x), t)
        return cls(
            params=host(trainer.params),
            opt_state=host(trainer.opt_state),
            step=trainer.step_num,
        )

    def restore_into(self, trainer) -> None:
        """Place this snapshot into ``trainer`` (replicated over its mesh)."""
        put = lambda t: jax.tree.map(
            lambda x: jax.device_put(x, trainer._replicated), t
        )
        trainer.params = put(self.params)
        trainer.opt_state = put(self.opt_state)
        trainer.step_num = self.step


class TrainerCheckpointer:
    """Durable on-disk checkpoints of trainer state via Orbax.

    Usage::

        ckpt = TrainerCheckpointer(dir)
        ckpt.save(trainer)                  # every k steps
        step = ckpt.restore(trainer)        # after restart / re-mesh
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3) -> None:
        self.directory = Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, trainer, *, force: bool = False) -> bool:
        if trainer.step_num in self._mgr.all_steps():
            return False  # this step is already durable; nothing to do
        state = {
            "params": trainer.params,
            "opt_state": trainer.opt_state,
            "step": trainer.step_num,
        }
        saved = self._mgr.save(
            trainer.step_num, args=ocp.args.StandardSave(state), force=force
        )
        self._mgr.wait_until_finished()
        return bool(saved)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, trainer, step: int | None = None) -> int:
        """Restore trainer state in place; returns the restored step number."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        # Use the trainer's live state as the abstract target so leaves come
        # back with the right dtypes/shardings for its current mesh.
        target = {
            "params": trainer.params,
            "opt_state": trainer.opt_state,
            "step": trainer.step_num,
        }
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        # Orbax may hand back single-device arrays; re-replicate over the
        # trainer's current mesh (this is also what makes restore-into-a-
        # different-mesh work after an elastic re-mesh).
        put = lambda t: jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), trainer._replicated)
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            t,
        )
        trainer.params = put(restored["params"])
        trainer.opt_state = put(restored["opt_state"])
        trainer.step_num = int(restored["step"])
        return trainer.step_num

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "TrainerCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
