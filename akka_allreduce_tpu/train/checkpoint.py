"""Checkpoint/resume for the DP trainer (SURVEY.md §6 "Checkpoint / resume").

The reference keeps no checkpoint state of its own — allreduce rounds are
stateless beyond the round window, and model save/load lives in its BIDMach
dependency. For capability parity of "resume after dropout" (BASELINE.json
config 5) the TPU build provides the trainer-layer equivalent: Orbax
checkpoints of ``{params, opt_state, step}``, plus a zero-copy in-memory
snapshot used by the elastic re-mesh path (SURVEY.md §8.4 — "checkpoint-in-HBM
→ reinit mesh → resume").
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P


def state_shardings(trainer) -> tuple[Any, Any]:
    """(param_shardings, opt_shardings) for placing restored state.

    Sharded trainers (TP / EP / PP — anything exposing ``_param_specs`` /
    ``_opt_specs`` PartitionSpec trees) get per-leaf NamedShardings over
    their CURRENT mesh; plain DP trainers fall back to the replicated
    sharding. Either way, restore works across a re-mesh: leaves are placed
    fresh onto whatever mesh the trainer has now.
    """
    mesh = getattr(trainer, "mesh", None)
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    def tree_of(specs):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs, is_leaf=is_spec
        )

    pspecs = getattr(trainer, "_param_specs", None)
    ospecs = getattr(trainer, "_opt_specs", None)
    p_sh = (
        tree_of(pspecs)
        if mesh is not None and pspecs is not None
        else trainer._replicated
    )
    o_sh = (
        tree_of(ospecs)
        if mesh is not None and ospecs is not None
        else trainer._replicated
    )
    return p_sh, o_sh


def place_on(tree, sharding) -> Any:
    """Device-put every array leaf of ``tree`` onto ``sharding`` (a single
    sharding for all leaves, or a matching tree of per-leaf shardings).

    jax.Array leaves reshard on device (a no-op when already placed — the
    Orbax restore target usually carries the right sharding, so no
    full-model host round trip); numpy leaves (Snapshot data) upload.
    """

    def put(x, s):
        if isinstance(x, (jax.Array, np.ndarray)):
            return jax.device_put(x, s)
        return x

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree.map(lambda x: put(x, sharding), tree)
    return jax.tree.map(put, tree, sharding)


def _restore_ef(trainer, ef) -> None:
    """Place a restored error-feedback residual, redistributing across a
    re-mesh: the residual is per-device withheld gradient mass, so when the
    device count changed we preserve its SUM (what the collective is still
    owed) by splitting it evenly over the new devices."""
    ef = np.asarray(ef, np.float32)
    n = trainer.n_devices
    if ef.shape[0] != n:
        ef = np.tile(ef.sum(axis=0, keepdims=True) / n, (n, 1))
    trainer._ef = jax.device_put(ef, trainer._data_sharding)


@dataclasses.dataclass
class Snapshot:
    """In-memory (host RAM) snapshot of trainer state for fast re-mesh resume.

    Held as numpy so it survives the death of the device mesh it came from:
    during elastic reconfiguration the old mesh's devices may be gone by the
    time we restore.

    Trainers with the trainer-defined checkpoint protocol
    (``checkpoint_state``/``restore_checkpoint_state`` — ZeRO-1, FSDP)
    snapshot through it: their serialized form is mesh-size-INDEPENDENT, so
    the same snapshot restores onto a mesh with a different device count —
    exactly what the elastic re-mesh needs (VERDICT r3 #3). Pytree-state
    trainers use the params/opt_state capture as before.
    """

    params: Any  # pytree of np.ndarray (pytree-state trainers)
    opt_state: Any  # pytree of np.ndarray / leaves
    step: int
    ef: Any = None  # error-feedback residual (n_devices, params) or None
    custom: dict | None = None  # trainer-defined checkpoint_state() payload

    @classmethod
    def capture(cls, trainer) -> "Snapshot":
        if hasattr(trainer, "checkpoint_state"):
            state = jax.tree.map(
                lambda x: np.asarray(x), dict(trainer.checkpoint_state())
            )
            return cls(
                params=None,
                opt_state=None,
                step=trainer.step_num,
                custom=state,
            )
        host = lambda t: jax.tree.map(lambda x: np.asarray(x), t)
        ef = getattr(trainer, "_ef", None)
        return cls(
            params=host(trainer.params),
            opt_state=host(trainer.opt_state),
            step=trainer.step_num,
            ef=None if ef is None else np.asarray(ef),
        )

    def restore_into(self, trainer) -> None:
        """Place this snapshot into ``trainer``, honoring its sharding layout
        (replicated for plain DP; per-leaf specs for TP/EP/PP trainers;
        the trainer-defined reshard for ZeRO-1/FSDP)."""
        if self.custom is not None:
            if not hasattr(trainer, "restore_checkpoint_state"):
                raise TypeError(
                    "snapshot was captured through a trainer-defined "
                    "checkpoint protocol; the restore target has none"
                )
            # restore may mutate the dict (zero1 pops format_version) and
            # the snapshot may be restored more than once — hand over a
            # shallow copy
            trainer.restore_checkpoint_state(dict(self.custom))
            trainer.step_num = self.step
            return
        p_sh, o_sh = state_shardings(trainer)
        trainer.params = place_on(self.params, p_sh)
        trainer.opt_state = place_on(self.opt_state, o_sh)
        trainer.step_num = self.step
        if self.ef is not None and getattr(trainer, "_ef", None) is not None:
            _restore_ef(trainer, self.ef)


class TrainerCheckpointer:
    """Durable on-disk checkpoints of trainer state via Orbax.

    Usage::

        ckpt = TrainerCheckpointer(dir)
        ckpt.save(trainer)                  # every k steps
        step = ckpt.restore(trainer)        # after restart / re-mesh
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3) -> None:
        self.directory = Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, trainer, *, force: bool = False) -> bool:
        if trainer.step_num in self._mgr.all_steps():
            return False  # this step is already durable; nothing to do
        if hasattr(trainer, "checkpoint_state"):
            # trainer-defined serialization (e.g. ZeRO-1's flat weights +
            # sharded optimizer state, which don't fit the params/opt_state
            # pytree shape)
            state = dict(trainer.checkpoint_state())
            state["step"] = trainer.step_num
        else:
            state = {
                "params": trainer.params,
                "opt_state": trainer.opt_state,
                "step": trainer.step_num,
            }
            if getattr(trainer, "_ef", None) is not None:
                # error-feedback residual is training state: dropping it on
                # restart would permanently lose every withheld gradient
                state["ef"] = trainer._ef
        saved = self._mgr.save(
            trainer.step_num, args=ocp.args.StandardSave(state), force=force
        )
        self._mgr.wait_until_finished()
        return bool(saved)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def _saved_top_keys(self, step: int) -> set | None:
        """Top-level keys of the saved tree (None if metadata unavailable).
        Lets restore reconcile OPTIONAL template keys with what the
        checkpoint actually carries (ADVICE r2: an EF/non-EF or version-key
        difference must not surface as a generic Orbax tree mismatch)."""
        try:
            return set(self._mgr.item_metadata(step).keys())
        except Exception:
            return None

    def restore(self, trainer, step: int | None = None) -> int:
        """Restore trainer state in place; returns the restored step number."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        if hasattr(trainer, "checkpoint_state"):
            # prefer the abstract template (shape/dtype only): building the
            # target must not gather the throwaway fresh state to host
            template_fn = getattr(
                trainer, "checkpoint_template", trainer.checkpoint_state
            )
            target = dict(template_fn())
            target["step"] = trainer.step_num
            saved = self._saved_top_keys(step)
            optional = getattr(trainer, "checkpoint_optional_keys", frozenset())
            if saved is not None:
                for k in optional:
                    # keys newer builds always write (format_version, the
                    # always-present ef_sum) may be absent from older
                    # checkpoints; drop them from the target rather than
                    # fail the whole restore on tree structure
                    if k in target and k not in saved:
                        target.pop(k)
            try:
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target)
                )
            except Exception as e:
                if (
                    "format_version" in optional
                    and saved is not None
                    and "format_version" not in saved
                ):
                    raise ValueError(
                        f"checkpoint step {step} under {self.directory} "
                        "predates this trainer's serialized format (no "
                        "format_version key — e.g. the round-1 padded "
                        "per-mesh ZeRO-1 layout) and cannot be loaded; "
                        "re-save it from the build that wrote it"
                    ) from e
                raise
            trainer.step_num = int(restored.pop("step"))
            trainer.restore_checkpoint_state(restored)
            return trainer.step_num
        # Use the trainer's live state as the abstract target so leaves come
        # back with the right dtypes/shardings for its current mesh.
        target = {
            "params": trainer.params,
            "opt_state": trainer.opt_state,
            "step": trainer.step_num,
        }
        has_ef = getattr(trainer, "_ef", None) is not None
        if has_ef:
            target["ef"] = trainer._ef
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        # Orbax may hand back single-device arrays; re-place onto the
        # trainer's CURRENT layout — replicated for plain DP, per-leaf
        # shardings for TP/EP/PP trainers (this is also what makes
        # restore-into-a-different-mesh work after an elastic re-mesh).
        p_sh, o_sh = state_shardings(trainer)
        trainer.params = place_on(restored["params"], p_sh)
        trainer.opt_state = place_on(restored["opt_state"], o_sh)
        trainer.step_num = int(restored["step"])
        if has_ef and "ef" in restored:
            _restore_ef(trainer, restored["ef"])
        return trainer.step_num

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "TrainerCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
