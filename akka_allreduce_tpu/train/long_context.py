"""Long-context trainer: DP x SP over a (data, seq) mesh.

Composes the framework's two pillars in one jitted SPMD step:

- **sequence parallelism** along the ``seq`` axis — each device holds a
  (B_local, T_local) token shard; attention runs as ring attention (K/V
  rotating over ICI neighbors) or Ulysses all-to-all (ops/ring_attention.py);
- **threshold-masked gradient allreduce** along BOTH axes — the same
  contributor-mask semantics as the reference's threshold allreduce
  (SURVEY.md §8.1 step 3), with the mask applied per DP *replica row*: a
  dropped/straggling replica's v=0 zeroes its whole row's contribution while
  the collective still completes, exactly the reference's partial-completion
  round recast over a 2D mesh.

The reference itself has neither sequence parallelism nor transformers
(SURVEY.md §6); this is the TPU rebuild's long-context layer.

Gradient collective: differentiating the v-weighted *local token-loss sum*
w.r.t. REPLICATED params makes shard_map autodiff insert the cross-device psum
over both mesh axes itself (the transpose of the params broadcast), so
``sum_d(v_row(d) * g_d)`` arrives in one fused collective; dividing by
``psum(v * local_token_count)`` yields the exact masked per-token-average
gradient. Same trick as train/trainer.py's unbucketed path.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class LongContextStepMetrics:
    step: int
    loss: float  # masked per-token average cross-entropy
    contributors: float  # contributing DP replica rows


class LongContextTrainer:
    """DP x SP (x TP) trainer for a :class:`~akka_allreduce_tpu.models.TransformerLM`.

    Args:
      model_cls: the TransformerLM class (or compatible); instantiated here so
        ``seq_axis`` always matches the mesh.
      mesh: a 2-axis (data, seq) mesh from ``parallel.data_seq_mesh``, or a
        3-axis (data, seq, model) mesh from ``parallel.data_seq_model_mesh``
        — the third axis adds Megatron-style tensor parallelism: attention
        heads and MLP hidden shard over it (``models.transformer.tp_param_specs``),
        one psum per projection pair completes the partials, and gradients
        for sharded leaves stay shard-local (shard_map's autodiff psums them
        over data/seq only, because those leaves enter device-varying on
        ``model``).
      seq_len: GLOBAL sequence length (divisible by the seq axis size).
      seq_impl: "ring" or "ulysses".
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        model_cls=None,
        vocab: int = 64,
        d_model: int = 64,
        n_heads: int = 4,
        n_kv_heads: int | None = None,
        n_layers: int = 2,
        seq_len: int = 128,
        seq_impl: str = "ring",
        optimizer: optax.GradientTransformation | None = None,
        learning_rate: float = 0.1,
        seed: int = 0,
        compute_dtype=jnp.float32,
        remat: bool = False,
        compress: str | None = None,
        overlap: bool = False,
    ) -> None:
        from akka_allreduce_tpu.models.transformer import (
            TransformerLM,
            tp_param_specs,
        )

        from akka_allreduce_tpu.comm.allreduce import validate_trainer_compress

        self.compress = validate_trainer_compress(compress, overlap=overlap)
        self.overlap = overlap

        if len(mesh.axis_names) not in (2, 3):
            raise ValueError(
                f"need a (data, seq[, model]) mesh, got axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.data_axis, self.seq_axis = mesh.axis_names[:2]
        self.model_axis = mesh.axis_names[2] if len(mesh.axis_names) == 3 else None
        self.dp = int(mesh.shape[self.data_axis])
        self.sp = int(mesh.shape[self.seq_axis])
        self.tp = (
            int(mesh.shape[self.model_axis]) if self.model_axis else 1
        )
        self.n_devices = self.dp * self.sp * self.tp
        self.data_shards = self.dp  # train_chain streams: one per replica row
        if seq_len % self.sp:
            raise ValueError(f"{seq_len=} not divisible by seq shards {self.sp}")
        self.seq_len = seq_len
        self.vocab = vocab
        cls = model_cls or TransformerLM
        self.model = cls(
            vocab=vocab,
            d_model=d_model,
            n_heads=n_heads,
            n_kv_heads=n_kv_heads,
            n_layers=n_layers,
            seq_axis=self.seq_axis,
            seq_impl=seq_impl,
            compute_dtype=compute_dtype,
            model_axis=self.model_axis if self.tp > 1 else None,
            tp_size=self.tp,
            remat=remat,
        )
        self.tx = optimizer or optax.adam(learning_rate)

        # init runs the module in single-device (dense, tp=1) form: FULL param
        # shapes. Under TP the shard_map in_specs below slice each leaf to the
        # local geometry the tp_size>1 module declares.
        init_model = cls(
            vocab=vocab,
            d_model=d_model,
            n_heads=n_heads,
            n_kv_heads=n_kv_heads,
            n_layers=n_layers,
            compute_dtype=compute_dtype,
        )
        tokens0 = jnp.zeros((1, seq_len // self.sp), jnp.int32)
        self.params = init_model.init(jax.random.PRNGKey(seed), tokens0)
        self.opt_state = self.tx.init(self.params)
        if self.tp > 1:
            assert self.model_axis is not None
            self._param_specs = tp_param_specs(self.params, self.model_axis)
            self._opt_specs = tp_param_specs(self.opt_state, self.model_axis)
        else:
            self._param_specs = jax.tree.map(lambda _: P(), self.params)
            self._opt_specs = jax.tree.map(lambda _: P(), self.opt_state)
        # place state on its shardings NOW: every step can then donate the
        # buffers in place instead of resharding (and warning) on first use
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        self.params = jax.device_put(
            self.params,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), self._param_specs,
                is_leaf=is_spec,
            ),
        )
        self.opt_state = jax.device_put(
            self.opt_state,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), self._opt_specs,
                is_leaf=is_spec,
            ),
        )
        self.param_count = int(
            sum(np.prod(p.shape) for p in jax.tree.leaves(self.params))
        )
        self.step_num = 0

        data_spec = P(self.data_axis, self.seq_axis)
        self._data_sharding = NamedSharding(mesh, data_spec)
        self._valid_sharding = NamedSharding(mesh, P(self.data_axis))
        axis_names = tuple(mesh.axis_names)
        data_axis = self.data_axis
        seq_axis = self.seq_axis
        vary_axes = tuple(n for n in axis_names if n != data_axis)
        model_apply = self.model.apply
        tx = self.tx
        param_specs = self._param_specs
        wire_dtype = jnp.bfloat16 if compress == "bf16" else None

        def step(params, opt_state, x, y, valid):
            # The mask arrives sharded on `data` only; mark it varying on the
            # other axes too so the all-axes psums below are well-typed (the
            # contributor count keeps the data-only form so its psum over
            # `data` is provably replicated). Under TP every model shard of a
            # (data, seq) coordinate computes the identical loss term, so the
            # all-axes denominator carries the same tp-fold factor as the
            # all-axes loss/grad sums — the ratio (and the per-leaf psum
            # transposes) come out exactly right at any tp.
            v0 = valid.reshape(())
            v = v0
            for ax in vary_axes:
                v = lax.pcast(v, ax, to="varying")
            tokens_local = jnp.float32(x.shape[0] * x.shape[1])
            denom = jnp.maximum(
                lax.psum(v * tokens_local, axis_names), 1.0
            )

            def masked_loss_sum(p):
                logits = model_apply(p, x)  # (B_local, T_local, vocab)
                ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
                return ce.sum() * v / denom

            if overlap:
                # per-leaf in-backward collectives (comm/compute overlap,
                # SURVEY.md §8.4): the loss is UNMASKED — each leaf's sync
                # masks its cotangent itself (sum_d v_d g_d) — so v is
                # folded back into the metric here
                from akka_allreduce_tpu.comm.allreduce import (
                    overlap_value_and_grad,
                )

                def unmasked_loss_sum(ps):
                    logits = model_apply(ps, x)
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        logits, y
                    )
                    return ce.sum() / denom

                lval, gavg = overlap_value_and_grad(
                    unmasked_loss_sum, params, param_specs, axis_names, v,
                    wire_dtype=wire_dtype,
                )
                lval = lval * v
            elif compress in ("bf16", "int8"):
                # wire compression needs the explicit collective: one
                # grouped collective per sharding class — bf16 psum at half
                # width, or the explicit int8 ring at a quarter — with
                # counts/denominator staying f32
                # (comm.allreduce.compressed_value_and_grad)
                from akka_allreduce_tpu.comm.allreduce import (
                    compressed_value_and_grad,
                )

                lval, gavg = compressed_value_and_grad(
                    masked_loss_sum, params, param_specs, axis_names,
                    wire_dtype=compress,
                )
            else:
                # EXPLICIT grouped psums even uncompressed: shard_map's
                # automatic transpose-psum for replicated params DOES NOT
                # RUN under check_vma=False (the flash-relax configs), so
                # relying on it would silently leave every device with its
                # LOCAL gradient — found by the runtime replica assert
                # (tests/test_vma_replication.py), VERDICT r4 #6
                from akka_allreduce_tpu.comm.allreduce import (
                    compressed_value_and_grad,
                )

                lval, gavg = compressed_value_and_grad(
                    masked_loss_sum, params, param_specs, axis_names,
                    wire_dtype=None,
                )
            loss_avg = lax.psum(lval, axis_names)  # masked, already /denom
            contributors = lax.psum(v0, data_axis)
            updates, new_opt = tx.update(gavg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt, loss_avg, contributors

        # The Pallas flash-attention kernel emits outputs with no varying-
        # axes annotation, so shard_map's static vma check cannot type it.
        # Relax the check ONLY when flash can actually dispatch for this
        # configuration (TPU backend + kernel-friendly shapes on a path that
        # runs a full local attention: sp==1, or Ulysses' local core);
        # everywhere else the check stays on — it is the static safety net.
        from akka_allreduce_tpu.ops.local_attention import flash_vma_relax

        self._check_vma = not overlap and compress != "int8" and not flash_vma_relax(
            seq_len, d_model // n_heads, sp=self.sp, seq_impl=seq_impl
        )
        mapped = jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(
                self._param_specs,
                self._opt_specs,
                data_spec,
                data_spec,
                P(self.data_axis),
            ),
            out_specs=(self._param_specs, self._opt_specs, P(), P()),
            check_vma=self._check_vma,
        )
        self._step = jax.jit(mapped, donate_argnums=(0, 1))
        self._raw_step = step  # reused by train_chain's on-device loop
        self._replicated = NamedSharding(mesh, P())
        self._chains: dict = {}

    # -- stepping ------------------------------------------------------------

    def _place(self, x, y):
        from akka_allreduce_tpu.train.trainer import place_tokens

        return place_tokens(
            x, y, self._data_sharding, seq_len=self.seq_len, dp=self.dp
        )

    def train_step(
        self,
        tokens: np.ndarray,
        labels: np.ndarray,
        valid: Sequence[float] | None = None,
    ) -> LongContextStepMetrics:
        """One step on a GLOBAL (batch, seq_len) token array.

        ``valid``: per-DP-replica-row contributor mask of shape (dp,);
        None = all rows contribute.
        """
        from akka_allreduce_tpu.train.trainer import (
            normalize_valid,
            place_mask,
        )

        valid_arr = normalize_valid(valid, self.dp)
        xd, yd = self._place(tokens, labels)
        vd = place_mask(valid_arr, self._valid_sharding)
        self.params, self.opt_state, loss, cnt = self._step(
            self.params, self.opt_state, xd, yd, vd
        )
        self.step_num += 1
        return LongContextStepMetrics(
            step=self.step_num, loss=float(loss), contributors=float(cnt)
        )

    def train(self, batches: Iterable) -> list[LongContextStepMetrics]:
        return [self.train_step(x, y) for x, y in batches]

    def get_flat_params(self) -> np.ndarray:
        from akka_allreduce_tpu.binder.api import flatten_pytree

        return flatten_pytree(self.params)[0]

    def set_flat_params(self, vec: np.ndarray) -> None:
        """Replace params from a flat float32 vector (binder/cluster seam),
        honoring the trainer's sharding layout (replicated or TP specs)."""
        from akka_allreduce_tpu.binder.api import flatten_pytree
        from akka_allreduce_tpu.train.checkpoint import (
            place_on,
            state_shardings,
        )

        # the tree structure never changes after __init__: build the
        # unflattener once, not one full device_get per sync round
        if getattr(self, "_unflatten", None) is None:
            _, self._unflatten = flatten_pytree(self.params)
        p_sh, _ = state_shardings(self)
        self.params = place_on(
            self._unflatten(np.asarray(vec, np.float32)), p_sh
        )

    # -- on-device training chain (data-loader path, no host I/O per step) ---

    def _build_chain(self, sampler, steps: int, rows_per_replica: int):
        raw_step = self._raw_step
        data_axis, seq_axis = self.data_axis, self.seq_axis
        t_local = self.seq_len // self.sp

        def chain(params, opt_state, key, valid):
            # one stream per DP replica ROW: all seq shards of a row fold the
            # same data-axis coordinate, so they agree on the row's tokens
            # and each slices its own T_local columns
            rkey = jax.random.fold_in(key, lax.axis_index(data_axis))
            s = lax.axis_index(seq_axis)

            def body(carry, i):
                p, o = carry
                k = jax.random.fold_in(rkey, i)
                x_g, y_g = sampler(k, rows_per_replica)
                x = lax.dynamic_slice_in_dim(x_g, s * t_local, t_local, axis=1)
                y = lax.dynamic_slice_in_dim(y_g, s * t_local, t_local, axis=1)
                p, o, loss, cnt = raw_step(p, o, x, y, valid)
                return (p, o), (loss, cnt)

            (params, opt_state), (losses, cnts) = lax.scan(
                body, (params, opt_state), jnp.arange(steps)
            )
            return params, opt_state, losses, cnts

        mapped = jax.shard_map(
            chain,
            mesh=self.mesh,
            in_specs=(self._param_specs, self._opt_specs, P(), P(data_axis)),
            out_specs=(self._param_specs, self._opt_specs, P(), P()),
            check_vma=self._check_vma,  # flash outputs carry no vma (see step)
        )
        return jax.jit(mapped, donate_argnums=(0, 1))

    def train_chain(
        self,
        sampler,
        steps: int,
        rows_per_replica: int,
        *,
        valid: Sequence[float] | None = None,
        seed: int = 0,
    ) -> list[LongContextStepMetrics]:
        """Run ``steps`` DP x SP steps entirely on device in ONE dispatch.

        ``sampler`` is a traced ``(key, rows) -> (tokens, labels)`` producing
        GLOBAL (rows, seq_len) sequences (``SyntheticCopyLM.device_sampler``);
        each replica row draws its own stream and its seq shards slice their
        local columns, so nothing crosses the host inside the loop.
        """
        from akka_allreduce_tpu.train.trainer import run_chain_cached

        losses, cnts = run_chain_cached(
            self,
            sampler,
            steps,
            rows_per_replica,
            lambda: self._build_chain(sampler, steps, rows_per_replica),
            valid,
            self.dp,
            self._valid_sharding,
            seed,
        )
        out = []
        for loss, cnt in zip(losses, cnts):
            self.step_num += 1
            out.append(
                LongContextStepMetrics(
                    step=self.step_num,
                    loss=float(loss),
                    contributors=float(cnt),
                )
            )
        return out
