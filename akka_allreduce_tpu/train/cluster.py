"""Distributed elastic-averaging training over the TCP cluster.

This is the reference's actual training deployment (SURVEY.md §4.4): per JVM,
a BIDMach learner trains while an ``AllreduceWorker`` asynchronously syncs the
model through the elastic-averaging binder — rounds overlap training steps and
thresholds keep stragglers from blocking anyone. Here, per node process: a
local ``DPTrainer`` steps on its own data shard in a worker thread while the
``NodeProcess`` (control/bootstrap.py) runs allreduce rounds over TCP.

Learner/binder coupling is asynchronous, as in the reference (and EASGD
generally): the binder never blocks on the learner. The learner thread
publishes a weight *snapshot* after each step; binder rounds read the latest
snapshot and deposit their elastic-averaged result in an incoming mailbox,
which the learner folds in before its next step. Both hand-offs are single
atomic reference swaps — no lock is ever held across a training step or a
round, so heartbeats keep flowing while the learner crunches (a step longer
than the heartbeat timeout must not get the node expelled).

The weights move over the wire as float chunks (host engine) because the
nodes are separate OS processes — the cross-process analog of the reference's
Netty data plane. Within one process, the TPU path syncs gradients in-step
via the fused masked psum instead (train/trainer.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Iterator

import numpy as np

from akka_allreduce_tpu.binder.elastic import ElasticAverageBinder
from akka_allreduce_tpu.control.bootstrap import NodeProcess
from akka_allreduce_tpu.control.cluster import Endpoint
from akka_allreduce_tpu.control.remote import observed_task

log = logging.getLogger(__name__)


class ElasticClusterNode:
    """One training node: local SGD + asynchronous weight allreduce.

    Args:
      seed: the master's endpoint.
      trainer: a ``DPTrainer`` (typically over this node's local devices).
      batches: iterator of ``(x, y)`` global batches for the LOCAL trainer.
      elastic_rate: pull strength toward the group average (reference
        ``NodeConfig.elastic_rate``).
    """

    def __init__(
        self,
        seed: Endpoint,
        trainer,
        batches: Iterator,
        *,
        elastic_rate: float = 0.5,
        host: str = "127.0.0.1",
        port: int = 0,
        preferred_node_id: int = -1,
        on_step: Callable[[object], None] | None = None,
    ) -> None:
        self.trainer = trainer
        self.batches = batches
        self.on_step = on_step
        # Cross-thread hand-off cells; every access is one reference
        # read/swap (atomic under the GIL), never a held lock:
        #   _snapshot: latest weights, published by the learner thread,
        #              read by binder rounds on the event loop;
        #   _incoming: latest elastic-averaged weights, deposited by the
        #              binder, consumed by the learner before its next step.
        self._snapshot: np.ndarray = trainer.get_flat_params()
        self._incoming: np.ndarray | None = None
        self.binder = ElasticAverageBinder(
            self._read_snapshot, self._deposit, elastic_rate
        )
        self.node = NodeProcess(
            seed,
            self.binder.data_source,
            self.binder.data_sink,
            host,
            port,
            preferred_node_id=preferred_node_id,
        )
        self.losses: list[float] = []

    # -- binder seam (runs on the transport event loop; must never block) ------

    def _read_snapshot(self) -> np.ndarray:
        return self._snapshot

    def _deposit(self, vec: np.ndarray) -> None:
        self._incoming = vec

    # -- learner thread --------------------------------------------------------

    def _train_one(self) -> bool:
        try:
            x, y = next(self.batches)
        except StopIteration:
            return False
        incoming, self._incoming = self._incoming, None
        if incoming is not None:
            self.trainer.set_flat_params(incoming)
        m = self.trainer.train_step(x, y)
        self._snapshot = self.trainer.get_flat_params()
        self.losses.append(m.loss)
        if self.on_step is not None:
            self.on_step(m)
        return True

    # -- lifecycle -------------------------------------------------------------

    async def run(self, max_steps: int | None = None) -> int:
        """Join the cluster, then train until the batches run out, ``max_steps``
        is reached, or the master broadcasts Shutdown. Returns steps taken."""
        await self.node.start()
        node_id = await self.node.wait_welcomed()
        expected = self.node.config.metadata.data_size
        if expected != self.trainer.param_count:
            raise ValueError(
                f"cluster data_size {expected} != model param count "
                f"{self.trainer.param_count}: master and nodes must be "
                "started with the same model flags"
            )
        log.info(
            "trainer node %d: %d params, elastic_rate=%.2f",
            node_id,
            self.trainer.param_count,
            self.binder.elastic_rate,
        )
        steps = 0
        shutdown = observed_task(
            self.node.run_until_shutdown(), name="shutdown-watch"
        )
        try:
            # A step budget is the node's own contract: train it to the end,
            # syncing while rounds last (the master finishing its round budget
            # first just means later steps run unsynced — the reference's
            # learners likewise never block on the allreduce). Only an
            # unbounded learner stops on the master's Shutdown.
            while max_steps is None or steps < max_steps:
                if max_steps is None and shutdown.done():
                    break
                stepped = await asyncio.to_thread(self._train_one)
                if not stepped:
                    break
                steps += 1
            if not shutdown.done():
                # master still running rounds: depart gracefully so the
                # remaining members re-line without detector latency
                await self.node.leave()
            # fold the final round's average in before reporting weights
            incoming, self._incoming = self._incoming, None
            if incoming is not None:
                self.trainer.set_flat_params(incoming)
        finally:
            if not shutdown.done():
                shutdown.cancel()
            await self.node.stop()
        return steps

    @property
    def rounds_applied(self) -> int:
        return self.binder.rounds_applied
