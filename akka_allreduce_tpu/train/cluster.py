"""Distributed elastic-averaging training over the TCP cluster.

This is the reference's actual training deployment (SURVEY.md §4.4): per JVM,
a BIDMach learner trains while an ``AllreduceWorker`` asynchronously syncs the
model through the elastic-averaging binder — rounds overlap training steps and
thresholds keep stragglers from blocking anyone. Here, per node process: a
local ``DPTrainer`` steps on its own data shard in a worker thread while the
``NodeProcess`` (control/bootstrap.py) runs allreduce rounds over TCP.

Learner/binder coupling is asynchronous, as in the reference (and EASGD
generally): the binder never blocks on the learner. The learner thread
publishes a weight *snapshot* after each step; binder rounds read the latest
snapshot and deposit their elastic-averaged result in an incoming mailbox,
which the learner folds in before its next step. Both hand-offs are single
atomic reference swaps — no lock is ever held across a training step or a
round, so heartbeats keep flowing while the learner crunches (a step longer
than the heartbeat timeout must not get the node expelled).

The weights move over the wire as float chunks (host engine) because the
nodes are separate OS processes — the cross-process analog of the reference's
Netty data plane. Within one process, the TPU path syncs gradients in-step
via the fused masked psum instead (train/trainer.py).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Iterator

import numpy as np

from akka_allreduce_tpu.binder.elastic import ElasticAverageBinder
from akka_allreduce_tpu.control.bootstrap import NodeProcess
from akka_allreduce_tpu.control.cluster import Endpoint
from akka_allreduce_tpu.control.remote import observed_task

log = logging.getLogger(__name__)


class ElasticClusterNode:
    """One training node: local SGD + asynchronous weight allreduce.

    Args:
      seed: the master's endpoint.
      trainer: a ``DPTrainer`` (typically over this node's local devices)
        — or an :class:`~akka_allreduce_tpu.train.elastic.ElasticTrainer`,
        which arms the tier-7 workload-resilience loop (RESILIENCE.md):
        the CLUSTER's membership view (AddressBook deltas, fed by the phi
        hub or SWIM gossip) drives the wrapper's snapshot -> rebuild ->
        restore re-mesh between steps, and the leader's per-round
        ``RoundPolicy`` wire stamp drives the trainer's ICI ``compress``
        mode through the trainer-factory rebuild path — ONE controller
        degrades both planes. Both applications run on the LEARNER
        thread (a rebuild re-jits; the event loop keeps heartbeating).
      batches: iterator of ``(x, y)`` global batches for the LOCAL
        trainer, or a callable ``trainer -> (x, y) | None`` for elastic
        trainers (the batch geometry follows the current mesh; None ends
        training).
      elastic_rate: pull strength toward the group average (reference
        ``NodeConfig.elastic_rate``).
    """

    def __init__(
        self,
        seed: Endpoint,
        trainer,
        batches: Iterator | Callable,
        *,
        elastic_rate: float = 0.5,
        host: str = "127.0.0.1",
        port: int = 0,
        preferred_node_id: int = -1,
        on_step: Callable[[object], None] | None = None,
        allow_crash: bool = False,
        chaos_log: str | None = None,
    ) -> None:
        self.trainer = trainer
        self.batches = batches
        self.on_step = on_step
        # tier-7 plumbing is armed by capability, not type (duck-typed so
        # this module stays importable without the elastic stack)
        self._elastic = hasattr(trainer, "apply_membership")
        # Cross-thread hand-off cells; every access is one reference
        # read/swap (atomic under the GIL), never a held lock:
        #   _snapshot: latest weights, published by the learner thread,
        #              read by binder rounds on the event loop;
        #   _incoming: latest elastic-averaged weights, deposited by the
        #              binder, consumed by the learner before its next step;
        #   _members: latest AddressBook membership, deposited by the
        #             event loop, applied by the learner before its next
        #             step (a second change landing during a restore just
        #             overwrites the cell — the learner re-meshes straight
        #             to the NEWEST view, never through the stale one).
        self._snapshot: np.ndarray = trainer.get_flat_params()
        self._incoming: np.ndarray | None = None
        self._members: tuple[int, ...] | None = None
        self._last_wire = ""
        self._policy_unsupported = False
        self.remeshes = 0
        self.compress_changes = 0
        self.paused = False  # below min_nodes: waiting for a rejoin
        self.binder = ElasticAverageBinder(
            self._read_snapshot, self._deposit, elastic_rate
        )
        self.node = NodeProcess(
            seed,
            self.binder.data_source,
            self.binder.data_sink,
            host,
            port,
            preferred_node_id=preferred_node_id,
            allow_crash=allow_crash,
            chaos_log=chaos_log,
        )
        if self._elastic:
            self.node.on_members = self._on_members
        self.losses: list[float] = []

    # -- binder seam (runs on the transport event loop; must never block) ------

    def _read_snapshot(self) -> np.ndarray:
        return self._snapshot

    def _deposit(self, vec: np.ndarray) -> None:
        self._incoming = vec

    def _on_members(self, members: tuple[int, ...]) -> None:
        # event-loop context: one cell swap, the learner applies it
        self._members = members

    # -- learner thread --------------------------------------------------------

    def _apply_cluster_view(self) -> None:
        """Fold the cluster's authoritative state into the local elastic
        trainer (learner-thread context — re-jits must not block the
        event loop): first the newest membership view, then the newest
        policy wire stamp. Both go through the wrapper's trainer-factory
        rebuild path, never a per-step retrace."""
        members, self._members = self._members, None
        if members is not None:
            try:
                if self.trainer.apply_membership(members):
                    self.remeshes += 1
            except RuntimeError as e:
                # e.g. a book snapshot without any assigned node (a
                # mid-rejoin view): keep stepping on the old mesh — the
                # next book lands in the cell and is applied then
                log.warning("membership %s not applied: %s", members, e)
        wire = self.node.policy_wire()
        if wire != self._last_wire and not self._policy_unsupported:
            self._last_wire = wire
            try:
                if self.trainer.apply_policy_wire(wire):
                    self.compress_changes += 1
                    log.info(
                        "policy wire %r -> ICI compress %s",
                        wire, self.trainer.compress_mode,
                    )
            except RuntimeError as e:
                # a factory without a `compress` kwarg has no rebuild path:
                # keep training at the construction mode (degrade is the
                # HOST wire's job then) — and stop re-trying every step
                self._policy_unsupported = True
                log.warning("policy wire %r not applied: %s", wire, e)

    def _next_batch(self):
        if callable(self.batches):
            return self.batches(self.trainer)
        try:
            return next(self.batches)
        except StopIteration:
            return None

    def _train_one(self) -> str:
        """One learner iteration: "stepped" (a real step ran), "paused"
        (below min_nodes — held position), or "end" (batches ran out)."""
        if self._elastic:
            self._apply_cluster_view()
            if self.trainer.n_nodes < self.trainer.min_nodes:
                # degrade, don't wedge — and don't crash: hold position
                # until the membership recovers (a rejoin re-grows the
                # mesh through the same cell). The binder keeps answering
                # rounds with the last snapshot meanwhile.
                self.paused = True
                time.sleep(0.2)
                return "paused"
            self.paused = False
        batch = self._next_batch()
        if batch is None:
            return "end"
        x, y = batch
        # hand-off cell contract (see __init__): one atomic reference swap,
        # deliberately lock-free so the binder's deposit never blocks a round
        incoming, self._incoming = (  # arlint: disable=THRD001 -- cell swap
            self._incoming, None,
        )
        if incoming is not None:
            self.trainer.set_flat_params(incoming)
        m = self.trainer.train_step(x, y)
        self._snapshot = self.trainer.get_flat_params()
        self.losses.append(m.loss)
        if self.on_step is not None:
            self.on_step(m)
        return "stepped"

    # -- lifecycle -------------------------------------------------------------

    async def run(
        self, max_steps: int | None = None, *, warmup_steps: int = 0
    ) -> int:
        """Join the cluster, then train until the batches run out, ``max_steps``
        is reached, or the master broadcasts Shutdown. Returns steps taken
        (warm-up included).

        ``warmup_steps`` run BEFORE the join: the learner compiles and
        takes its first steps locally, so the node enters the sync fabric
        with weights worth averaging — and a drill's round-triggered
        faults (the master organizes, and rounds start, only once every
        node joined) land on nodes that are genuinely mid-training."""
        warmed = 0
        for _ in range(warmup_steps):
            if await asyncio.to_thread(self._train_one) != "stepped":
                break
            warmed += 1
        await self.node.start()
        node_id = await self.node.wait_welcomed()
        expected = self.node.config.metadata.data_size
        if expected != self.trainer.param_count:
            raise ValueError(
                f"cluster data_size {expected} != model param count "
                f"{self.trainer.param_count}: master and nodes must be "
                "started with the same model flags"
            )
        log.info(
            "trainer node %d: %d params, elastic_rate=%.2f",
            node_id,
            self.trainer.param_count,
            self.binder.elastic_rate,
        )
        steps = warmed
        shutdown = observed_task(
            self.node.run_until_shutdown(), name="shutdown-watch"
        )
        try:
            # A step budget is the node's own contract: train it to the end,
            # syncing while rounds last (the master finishing its round budget
            # first just means later steps run unsynced — the reference's
            # learners likewise never block on the allreduce). Only an
            # unbounded learner stops on the master's Shutdown.
            while max_steps is None or steps < max_steps:
                if max_steps is None and shutdown.done():
                    break
                outcome = await asyncio.to_thread(self._train_one)
                if outcome == "end":
                    break
                if outcome == "paused" and shutdown.done():
                    # a bounded learner normally ignores Shutdown ("train
                    # it to the end"), but a paused one cannot make
                    # progress by definition — holding position past the
                    # cluster's end would spin forever
                    break
                if outcome == "stepped":
                    steps += 1
            if not shutdown.done():
                # master still running rounds: depart gracefully so the
                # remaining members re-line without detector latency
                await self.node.leave()
            # fold the final round's average in before reporting weights
            # (same lock-free hand-off cell swap as _train_one)
            incoming, self._incoming = (  # arlint: disable=THRD001 -- cell swap
                self._incoming, None,
            )
            if incoming is not None:
                self.trainer.set_flat_params(incoming)
        finally:
            if not shutdown.done():
                shutdown.cancel()
            await self.node.stop()
        return steps

    @property
    def rounds_applied(self) -> int:
        return self.binder.rounds_applied
