"""The trainer zoo for workload-resilience drills (RESILIENCE.md "Tier 7").

One registry of the real trainer families the ``chaos-train`` drill (and
its tier-1 tests) can put through the elastic cycle: for each family a
mesh-size-independent :class:`~akka_allreduce_tpu.train.elastic.ElasticTrainer`
factory whose inner trainer factory takes a ``compress`` kwarg — so the
SAME wrapper rides both halves of tier 7:

- **membership re-meshes** (snapshot -> rebuild over the live devices ->
  restore) driven by the TCP cluster's failure detector, and
- **compress-follows-policy** rebuilds driven by the leader's
  :class:`~akka_allreduce_tpu.protocol.RoundPolicy` wire stamp
  (``ElasticTrainer.apply_policy_wire``).

Shapes are drill-sized (tiny models, loopback CPU meshes): the point is
the RESILIENCE machinery over the real step functions, not throughput —
BENCHMARKS.md owns the flagship shapes.

Family notes:

- ``dp``: the config-5 workhorse (MLP + DPTrainer). Error feedback rides
  every compressed mode, so a policy ladder walk exercises the residual
  carry across factory rebuilds.
- ``zero1``: sharded optimizer state (momentum) through the
  mesh-size-independent checkpoint protocol. Its reduce-scatter has no
  int8 ring, so an ``int8`` stamp degrades to the deepest mode the family
  has (``bf16``) instead of refusing — degrade, not wedge.
- ``fsdp``: params AND moments sharded 1/n; restage = re-shard.
- ``pipeline``: the hard case — the trunk restages L/S' layers per stage
  over the surviving ``pipe`` axis (gcd rule), falling back to a DP-only
  mesh when only one stage's worth of devices survives.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping, Sequence

__all__ = [
    "FAMILIES",
    "batch_for",
    "devices_per_node",
    "family_param_count",
    "make_elastic",
]

FAMILIES = ("dp", "zero1", "fsdp", "pipeline")

#: virtual devices each cluster node contributes to the local mesh —
#: pipeline gets 2 so a node loss RESTAGES (8 devs / 4 stages -> 6 devs /
#: 2 stages) instead of only shrinking dp
_DEVICES_PER_NODE = {"dp": 1, "zero1": 1, "fsdp": 1, "pipeline": 2}

_PIPE_LAYERS = 4
_PIPE_MICRO = 2
_SEQ_LEN = 32
_VOCAB = 16


@dataclasses.dataclass(frozen=True)
class _Family:
    make: Callable  # (devices_by_node, seed, clock, min_nodes) -> elastic
    rows: Callable  # live trainer -> global batch rows (re-mesh aware)
    dataset: Callable  # () -> dataset with .batches(rows, steps, seed_offset)


def devices_per_node(family: str) -> int:
    _require(family)
    return _DEVICES_PER_NODE[family]


def _require(family: str) -> None:
    if family not in FAMILIES:
        raise ValueError(f"family must be one of {FAMILIES}, got {family!r}")


def _mnist():
    from akka_allreduce_tpu.models import data

    return data.mnist_like()


def _lm():
    from akka_allreduce_tpu.models import data

    return data.lm_copy_task(_SEQ_LEN, vocab=_VOCAB)


def _make_dp(devices_by_node, seed, clock, min_nodes):
    import numpy as np

    from akka_allreduce_tpu.models import MLP
    from akka_allreduce_tpu.train.elastic import ElasticTrainer
    from akka_allreduce_tpu.train.trainer import DPTrainer

    model = MLP(hidden=(16,), classes=10)
    ex = np.zeros((1, 28, 28, 1), np.float32)

    def factory(mesh, compress=None):
        return DPTrainer(
            model,
            mesh,
            example_input=ex,
            learning_rate=0.1,
            seed=seed,
            compress=compress,
            # the residual carry is the family's EF story: active under
            # every lossy mode, rebuilt across level changes via Snapshot
            error_feedback=compress is not None,
        )

    return ElasticTrainer(
        factory, devices_by_node, min_nodes=min_nodes, clock=clock
    )


def _make_zero1(devices_by_node, seed, clock, min_nodes):
    import numpy as np
    import optax

    from akka_allreduce_tpu.models import MLP
    from akka_allreduce_tpu.train.elastic import ElasticTrainer
    from akka_allreduce_tpu.train.zero1 import Zero1DPTrainer

    model = MLP(hidden=(16,), classes=10)
    ex = np.zeros((1, 28, 28, 1), np.float32)

    def factory(mesh, compress=None):
        return Zero1DPTrainer(
            model,
            mesh,
            example_input=ex,
            # momentum makes the sharded moments REAL state: a re-mesh
            # that dropped them would visibly bend the loss curve
            optimizer=optax.sgd(0.1, momentum=0.9),
            seed=seed,
            compress=compress,
            error_feedback=compress is not None,
        )

    e = ElasticTrainer(
        factory, devices_by_node, min_nodes=min_nodes, clock=clock
    )
    # ZeRO-1's reduce-scatter has no int8 ring: the deepest stamp degrades
    # to bf16 — the family's floor — instead of refusing. The clamp lives
    # on the WRAPPER so an int8 stamp arriving while already at bf16 is a
    # recognized no-op, not a full factory rebuild of the same trainer.
    e.clamp_compress = lambda mode: "bf16" if mode else None
    return e


def _make_fsdp(devices_by_node, seed, clock, min_nodes):
    import optax

    from akka_allreduce_tpu.train.elastic import ElasticTrainer
    from akka_allreduce_tpu.train.fsdp import FSDPLMTrainer

    def factory(mesh, compress=None):
        return FSDPLMTrainer(
            mesh,
            vocab=_VOCAB,
            d_model=32,
            n_heads=4,
            n_layers=2,
            seq_len=_SEQ_LEN,
            optimizer=optax.adam(1e-2),
            seed=seed,
            compress=compress,
        )

    return ElasticTrainer(
        factory, devices_by_node, min_nodes=min_nodes, clock=clock
    )


def _make_pipeline(devices_by_node, seed, clock, min_nodes):
    from akka_allreduce_tpu.train.elastic import ElasticPipelineTrainer

    return ElasticPipelineTrainer(
        devices_by_node,
        n_layers=_PIPE_LAYERS,
        microbatches=_PIPE_MICRO,
        vocab=_VOCAB,
        d_model=32,
        n_heads=2,
        seq_len=_SEQ_LEN,
        learning_rate=1e-2,
        seed=seed,
        # hand-scheduled 1F1B: grouped collectives, so bf16/int8 policy
        # rebuilds exercise the compressed epilogue
        schedule="1f1b",
        min_nodes=min_nodes,
        clock=clock,
    )


_REGISTRY: dict[str, _Family] = {
    "dp": _Family(
        make=_make_dp,
        rows=lambda t: 4 * t.n_devices,
        dataset=_mnist,
    ),
    "zero1": _Family(
        make=_make_zero1,
        rows=lambda t: 4 * t.n_devices,
        dataset=_mnist,
    ),
    "fsdp": _Family(
        make=_make_fsdp,
        rows=lambda t: 2 * t.n_devices,
        dataset=_lm,
    ),
    "pipeline": _Family(
        make=_make_pipeline,
        rows=lambda t: t.trainer.dp * _PIPE_MICRO,
        dataset=_lm,
    ),
}


def make_elastic(
    family: str,
    devices_by_node: Mapping[int, Sequence],
    *,
    seed: int = 0,
    clock=None,
    min_nodes: int = 1,
):
    """Build the family's ElasticTrainer over ``devices_by_node``."""
    import time

    _require(family)
    return _REGISTRY[family].make(
        devices_by_node, seed, clock or time.monotonic, min_nodes
    )


def dataset_for(family: str):
    _require(family)
    return _REGISTRY[family].dataset()


def batch_for(family: str, dataset, elastic, seed_offset: int):
    """One global batch sized for the LIVE trainer (re-mesh aware: the
    row count follows the current dp extent)."""
    _require(family)
    rows = _REGISTRY[family].rows(elastic)
    return next(iter(dataset.batches(rows, 1, seed_offset=seed_offset)))


def family_param_count(family: str) -> int:
    """The family model's (mesh-independent) parameter count — what sizes
    the cluster's ``data_size``. Built on a single device; cheap."""
    import jax

    _require(family)
    e = make_elastic(family, {0: [jax.devices()[0]]})
    return int(e.trainer.param_count)
