"""Pipeline-parallel Transformer training: DP x PP over a (data, pipe) mesh.

Beyond-parity capability (the reference is DP-only, SURVEY.md §3) and the
last of the classic strategies (DP/SP/TP/EP/ZeRO elsewhere in train/). Built
the TPU way — no host scheduler, no per-stage processes: the WHOLE pipeline
is one jitted SPMD program.

- The transformer trunk's L layers stack into one params tree with a leading
  layer dim, sharded ``P('pipe')``: each of the S stages holds L/S layers and
  runs them with a local ``lax.scan``.
- GPipe-style execution (``schedule="gpipe"``) is a second ``lax.scan``
  over ``M + S - 1`` ticks: every tick each stage applies its layers and
  hands its activation to the next stage with ONE ``ppermute`` hop over the
  ``pipe`` axis (neighbor traffic on the ICI torus). Stage 0 injects a
  fresh microbatch per tick; the last stage peels off finished microbatches
  and accumulates the loss. The (S-1)/(M+S-1) bubble is the standard GPipe
  trade.
- Autodiff differentiates straight through both scans: the reverse pass IS
  backward pipelining (cotangents ride the reverse ppermute), trunk
  gradients stay stage-local (the leaves enter shard_map device-varying on
  ``pipe``), and the replicated embed/head gradients are completed by the
  same transpose-psum mechanism as every other trainer here. The memory
  cost of that elegance: the scan saves every tick's carry for the reverse
  pass, so each stage holds O(M) in-flight microbatch activations.
- ``schedule="1f1b"`` (VERDICT r3 #4) hand-schedules forward AND backward
  in one scan over ``M + 2S - 2`` ticks, so memory is O(S) instead of
  O(M): forwards flow exactly like GPipe (micro f runs on stage s at tick
  ``s + f``), while micro b's backward runs on stage s at tick
  ``2(S-1) - s + b`` — the LAST stage backs up micro b in the same tick
  that forwarded it, and cotangents hop one stage per tick on the reverse
  ppermute. Each stage keeps only a ``2S - 1``-slot ring of pending stage
  INPUTS (the static proof of the O(S) bound: the scan carry IS the live
  state — no AD runs over the tick loop) and recomputes the stage forward
  inside its backward tick's ``jax.vjp`` (the remat trade built in).
  Gradients are accumulated per tick and completed by ONE explicit grouped
  collective per sharding class (``comm.allreduce.grouped_tree_psum`` —
  bf16/int8 wire compression compose unchanged); numerics match GPipe to
  float reassociation (same per-micro terms, summed in tick order instead
  of reverse-AD order).
- Threshold masking: the contributor mask is per DP replica row, exactly as
  in DPTrainer/LongContextTrainer — a dropped row zeroes its contribution
  while the collective completes.

Numerics are EXACT vs the unpipelined model (microbatching only reorders the
same sums), which is what the tests assert.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class PipelineStepMetrics:
    step: int
    loss: float  # masked per-token cross-entropy
    contributors: float  # contributing DP replica rows


class _LMHead(nn.Module):
    vocab: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        return nn.Dense(self.vocab, dtype=self.compute_dtype)(x).astype(
            jnp.float32
        )


class PipelineLMTrainer:
    """DP x PP trainer for a decoder-only Transformer LM.

    Args:
      mesh: a (data, pipe) 2-axis mesh (``pipe`` may be 1 = no pipelining,
        which is also the oracle the tests compare against).
      layers_per_stage: trunk depth per pipeline stage (total layers =
        layers_per_stage * pipe).
      microbatches: GPipe microbatches per step; the per-device batch must
        divide by it. More microbatches = smaller bubble, smaller matmuls.
    """

    @staticmethod
    def validate_flags(
        *,
        schedule: str = "gpipe",
        virtual_chunks: int = 1,
        layers_per_stage: int = 1,
        overlap: bool = False,
    ) -> None:
        """Raise ValueError for schedule/virtual/overlap combinations the
        trainer cannot run. Pure flag checks (no mesh/model state) so CLIs
        can convert them to usage errors BEFORE construction — one source
        of truth instead of hand-copied checks."""
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"schedule must be gpipe, 1f1b or interleaved, got {schedule!r}"
            )
        if schedule in ("1f1b", "interleaved") and overlap:
            raise ValueError(
                "overlap excludes the hand-scheduled pipelines: their "
                "gradients are accumulated per tick (no backward pass for "
                "the per-leaf sync to hook); the grouped collective already "
                "fires once at the end of the tick scan"
            )
        if schedule == "interleaved":
            if virtual_chunks < 2:
                raise ValueError(
                    "schedule='interleaved' needs virtual_chunks >= 2 "
                    "(1 chunk IS plain 1f1b — use schedule='1f1b')"
                )
            if layers_per_stage % virtual_chunks:
                raise ValueError(
                    f"{layers_per_stage=} not divisible by "
                    f"{virtual_chunks=} chunks"
                )
        elif virtual_chunks != 1:
            raise ValueError(
                f"virtual_chunks={virtual_chunks} only applies to "
                "schedule='interleaved'"
            )

    def __init__(
        self,
        mesh: Mesh,
        *,
        vocab: int = 64,
        d_model: int = 64,
        n_heads: int = 4,
        n_kv_heads: int | None = None,
        layers_per_stage: int = 1,
        microbatches: int = 2,
        seq_len: int = 64,
        optimizer: optax.GradientTransformation | None = None,
        learning_rate: float = 1e-2,
        seed: int = 0,
        compute_dtype=jnp.float32,
        remat: bool = False,
        compress: str | None = None,
        overlap: bool = False,
        schedule: str = "gpipe",
        virtual_chunks: int = 1,
    ) -> None:
        from akka_allreduce_tpu.models.transformer import Block

        if len(mesh.axis_names) != 2:
            raise ValueError(
                f"need a (data, pipe) mesh, got axes {mesh.axis_names}"
            )
        self.validate_flags(
            schedule=schedule,
            virtual_chunks=virtual_chunks,
            layers_per_stage=layers_per_stage,
            overlap=overlap,
        )
        from akka_allreduce_tpu.comm.allreduce import validate_trainer_compress

        self.compress = validate_trainer_compress(compress, overlap=overlap)
        self.overlap = overlap
        self.schedule = schedule
        self.mesh = mesh
        self.data_axis, self.pipe_axis = mesh.axis_names
        self.dp = int(mesh.shape[self.data_axis])
        self.stages = int(mesh.shape[self.pipe_axis])
        self.n_devices = self.dp * self.stages
        self.microbatches = microbatches
        self.seq_len = seq_len
        self.vocab = vocab
        self.n_layers = layers_per_stage * self.stages
        self.tx = optimizer or optax.adam(learning_rate)

        block = Block(
            n_heads=n_heads, n_kv_heads=n_kv_heads,
            compute_dtype=compute_dtype,
        )
        embed = nn.Embed(vocab, d_model, dtype=compute_dtype)
        head = _LMHead(vocab, compute_dtype=compute_dtype)
        rng = jax.random.PRNGKey(seed)
        x0 = jnp.zeros((1, seq_len, d_model), jnp.float32)
        tok0 = jnp.zeros((1, seq_len), jnp.int32)
        layer_ps = [
            block.init(jax.random.fold_in(rng, 1000 + i), x0)["params"]
            for i in range(self.n_layers)
        ]
        # stack to (L, ...) leaves: ONE trunk tree, layer dim sharded on
        # pipe. Interleaved: stage s's local rows hold its v chunks in
        # chunk order, and chunk c of stage s is the LOGICAL block c*S + s
        # (a microbatch loops the ring v times, visiting blocks in logical
        # order), so the stacked row s*lps + c*cl + j carries logical
        # layer (c*S + s)*cl + j. _layer_perm maps stacked -> logical;
        # everything external (get_flat_params, checkpoints) sees logical.
        lps = layers_per_stage
        cl = lps // virtual_chunks
        self._layer_perm = np.arange(self.n_layers)
        if schedule == "interleaved":
            self._layer_perm = np.array(
                [
                    (c * self.stages + s) * cl + j
                    for s in range(self.stages)
                    for c in range(virtual_chunks)
                    for j in range(cl)
                ]
            )
        self._layer_perm_inv = np.argsort(self._layer_perm)
        trunk = jax.tree.map(
            lambda *ls: jnp.stack([ls[g] for g in self._layer_perm]),
            *layer_ps,
        )
        self.virtual_chunks = virtual_chunks
        self.params = {
            "embed": embed.init(jax.random.fold_in(rng, 1), tok0)["params"],
            "trunk": trunk,
            "head": head.init(jax.random.fold_in(rng, 2), x0)["params"],
        }
        self.opt_state = self.tx.init(self.params)
        self.param_count = int(
            sum(np.prod(p.shape) for p in jax.tree.leaves(self.params))
        )
        self.step_num = 0

        # one rule for params AND optax moments: any leaf whose path passes
        # through 'trunk' shards its leading (layer) dim on the pipe axis
        def stage_spec(path, leaf):
            names = [
                str(getattr(k, "key", getattr(k, "name", k))) for k in path
            ]
            if "trunk" in names:
                return P(*([self.pipe_axis] + [None] * (leaf.ndim - 1)))
            return P()

        self._param_specs = jax.tree_util.tree_map_with_path(
            stage_spec, self.params
        )
        self._opt_specs = jax.tree_util.tree_map_with_path(
            stage_spec, self.opt_state
        )
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        self.params = jax.device_put(
            self.params,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), self._param_specs,
                is_leaf=is_spec,
            ),
        )
        self.opt_state = jax.device_put(
            self.opt_state,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), self._opt_specs,
                is_leaf=is_spec,
            ),
        )

        axis_names = tuple(mesh.axis_names)
        data_axis, pipe_axis = self.data_axis, self.pipe_axis
        s_count = self.stages
        m_count = microbatches
        tx = self.tx
        param_specs = self._param_specs
        wire_dtype = jnp.bfloat16 if compress == "bf16" else None
        block_apply = block.apply
        embed_apply = embed.apply
        head_apply = head.apply

        def run_stage(trunk_local, h):
            """Apply this stage's layers_per_stage blocks sequentially;
            with ``remat`` each layer recomputes on backward (jax.checkpoint)
            so a stage holds one layer's activations, not layers_per_stage —
            the memory knob for deep stages and long sequences."""

            def body(carry, layer_p):
                return block_apply({"params": layer_p}, carry), None

            if remat:
                body = jax.checkpoint(body)
            out, _ = lax.scan(body, h, trunk_local)
            return out

        fwd = [(i, (i + 1) % s_count) for i in range(s_count)]

        def stage_context(x, valid):
            """The prologue BOTH schedules share — any change to masking or
            the loss denominator lands in one place, preserving the tested
            GPipe/1F1B equivalence by construction."""
            s = lax.axis_index(pipe_axis)
            v0 = valid.reshape(())
            v = lax.pcast(v0, pipe_axis, to="varying")
            b_local, t_len = x.shape
            if b_local % m_count:
                raise ValueError(
                    f"per-device batch {b_local} not divisible by "
                    f"{m_count} microbatches"
                )
            mb = b_local // m_count
            is_last = s == s_count - 1
            # only the last stage carries loss tokens; no double counting
            denom = jnp.maximum(
                lax.psum(
                    v
                    * jnp.float32(b_local * t_len)
                    * is_last.astype(jnp.float32),
                    axis_names,
                ),
                1.0,
            )
            return s, v0, v, mb, t_len, is_last, denom

        def apply_update(params, opt_state, gavg):
            updates, new_opt = tx.update(gavg, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        def stage_all(trunk_local, head_p, inp, lbl):
            """One stage's (or chunk's) whole tick-work: blocks, then
            head+loss. The single vjp point for BOTH hand-scheduled
            cotangent paths — mid stages seed d(out) with the received
            cotangent (d(ce)=0, so the head contributes nothing), the last
            stage seeds d(ce)=1. Shared by 1f1b and interleaved so the
            schedules can never diverge in per-tick math."""
            out = run_stage(trunk_local, inp)
            logits = head_apply({"params": head_p}, out)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, lbl
            ).sum()
            return out, ce

        def hand_epilogue(
            params, opt_state, g_emb, g_trunk, g_head, ce_total, v, v0, denom
        ):
            """Shared tail of the hand-scheduled schedules: mask-scale the
            accumulated grads, ONE grouped collective per sharding class
            (bf16/int8 wire compression composes here), loss psum, update."""
            grads = {"embed": g_emb, "trunk": g_trunk, "head": g_head}
            scale = v / denom
            grads = jax.tree.map(
                lambda g: g * scale.astype(g.dtype), grads
            )
            from akka_allreduce_tpu.comm.allreduce import grouped_tree_psum

            gavg = grouped_tree_psum(
                grads, param_specs, axis_names, wire_dtype=compress
            )
            loss_avg = lax.psum(ce_total * v / denom, axis_names)
            contributors = lax.psum(v0, data_axis)
            new_params, new_opt = apply_update(params, opt_state, gavg)
            return new_params, new_opt, loss_avg, contributors

        def step(params, opt_state, x, y, valid):
            s, v0, v, mb, t_len, is_last_b, denom = stage_context(x, valid)
            is_last = is_last_b.astype(jnp.float32)

            def pipeline_ce(p):
                """The GPipe forward: this device's summed loss tokens
                (nonzero only on the last stage's real microbatches)."""
                xe = embed_apply({"params": p["embed"]}, x)
                micro = xe.reshape(m_count, mb, t_len, -1)
                labels = y.reshape(m_count, mb, t_len)

                def tick(carry, t):
                    received = carry
                    # stage 0 injects microbatch t (clamped; ticks past M
                    # feed garbage that exits after the loop ends)
                    inj = lax.dynamic_index_in_dim(
                        micro, jnp.clip(t, 0, m_count - 1), 0, keepdims=False
                    )
                    inp = jnp.where(s == 0, inj, received)
                    out = run_stage(p["trunk"], inp)
                    nxt = lax.ppermute(out, pipe_axis, fwd)
                    # last stage peels microbatch m = t - (S-1) when it is real
                    m = t - (s_count - 1)
                    logits = head_apply({"params": p["head"]}, out)
                    lbl = lax.dynamic_index_in_dim(
                        labels, jnp.clip(m, 0, m_count - 1), 0, keepdims=False
                    )
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        logits, lbl
                    ).sum()
                    take = ((s == s_count - 1) & (m >= 0)).astype(jnp.float32)
                    return nxt, ce * take

                zero = jnp.zeros((mb, t_len, xe.shape[-1]), xe.dtype)
                # the carry becomes device-varying after its first ppermute
                # hop; the initial value must carry the same vma type
                zero = lax.pcast(zero, axis_names, to="varying")
                _, ces = lax.scan(
                    tick, zero, jnp.arange(m_count + s_count - 1)
                )
                return ces.sum()

            def masked_loss(p):
                ce_total = pipeline_ce(p)
                return ce_total * v / denom, ce_total

            if overlap:
                # per-leaf in-backward collectives (SURVEY.md §8.4): the
                # loss is UNMASKED — each leaf's sync masks its cotangent;
                # loss_avg below re-applies v explicitly
                from akka_allreduce_tpu.comm.allreduce import (
                    overlap_value_and_grad,
                )

                def unmasked_loss(ps):
                    ce_total = pipeline_ce(ps)
                    return ce_total / denom, ce_total

                (_, ce_total), gavg = overlap_value_and_grad(
                    unmasked_loss, params, param_specs, axis_names, v,
                    has_aux=True, wire_dtype=wire_dtype,
                )
            elif compress in ("bf16", "int8"):
                # explicit grouped collective (see long_context.py);
                # trunk leaves (pipe-sharded) reduce over data only,
                # embed/head over data x pipe; int8 rides the explicit
                # ring per reduce axis
                from akka_allreduce_tpu.comm.allreduce import (
                    compressed_value_and_grad,
                )

                (_, ce_total), gavg = compressed_value_and_grad(
                    masked_loss, params, param_specs, axis_names,
                    has_aux=True,
                    wire_dtype=compress,
                )
            else:
                # explicit grouped psums even uncompressed: the automatic
                # transpose-psum for replicated params does not run under
                # check_vma=False (flash-relax configs) — see
                # long_context.py / tests/test_vma_replication.py
                from akka_allreduce_tpu.comm.allreduce import (
                    compressed_value_and_grad,
                )

                (_, ce_total), gavg = compressed_value_and_grad(
                    masked_loss, params, param_specs, axis_names,
                    has_aux=True,
                    wire_dtype=None,
                )
            loss_avg = lax.psum(ce_total * v * is_last / denom, axis_names)
            contributors = lax.psum(v0, data_axis)
            new_params, new_opt = apply_update(params, opt_state, gavg)
            return new_params, new_opt, loss_avg, contributors

        rev = [(i, (i - 1) % s_count) for i in range(s_count)]
        # max pending stage inputs under the 1f1b schedule: stage s holds
        # 2*(S-1-s) + 1 in-flight microbatches (forwards outpace backwards
        # by exactly the cotangent round trip) — bounded by 2S-1, O(S) and
        # M-independent. This ring IS the schedule's memory bound: no AD
        # runs over the tick scan, so the carry is the whole live state.
        ring_k = 2 * s_count - 1

        def step_1f1b(params, opt_state, x, y, valid):
            s, v0, v, mb, t_len, is_last, denom = stage_context(x, valid)
            micro_tok = x.reshape(m_count, mb, t_len)
            labels = y.reshape(m_count, mb, t_len)

            def tick(carry, t):
                ring, act_rx, ct_rx, g_emb, g_trunk, g_head, ce_acc = carry
                # ---- forward: micro f = t - s (GPipe pacing) ----
                f = t - s
                do_f = (f >= 0) & (f < m_count)
                fc = jnp.clip(f, 0, m_count - 1)
                tok_f = lax.dynamic_index_in_dim(
                    micro_tok, fc, 0, keepdims=False
                )
                lbl_f = lax.dynamic_index_in_dim(
                    labels, fc, 0, keepdims=False
                )
                emb_f = embed_apply({"params": params["embed"]}, tok_f)
                inp = jnp.where(s == 0, emb_f, act_rx)
                slot_f = jnp.mod(fc, ring_k)
                prev = lax.dynamic_slice_in_dim(ring, slot_f, 1, axis=0)[0]
                ring = lax.dynamic_update_slice_in_dim(
                    ring, jnp.where(do_f, inp, prev)[None], slot_f, axis=0
                )
                out_f, ce_f = stage_all(
                    params["trunk"], params["head"], inp, lbl_f
                )
                send = lax.ppermute(out_f, pipe_axis, fwd)
                ce_acc = ce_acc + ce_f * (
                    is_last & do_f
                ).astype(jnp.float32)

                # ---- backward: micro b = t - 2(S-1) + s ----
                b = t - 2 * (s_count - 1) + s
                do_b = (b >= 0) & (b < m_count)
                do_bf = do_b.astype(jnp.float32)
                bc = jnp.clip(b, 0, m_count - 1)
                slot_b = jnp.mod(bc, ring_k)
                inp_b = lax.dynamic_slice_in_dim(ring, slot_b, 1, axis=0)[0]
                tok_b = lax.dynamic_index_in_dim(
                    micro_tok, bc, 0, keepdims=False
                )
                lbl_b = lax.dynamic_index_in_dim(
                    labels, bc, 0, keepdims=False
                )
                (out_b, _), vjp_fn = jax.vjp(
                    lambda tr, hp, i: stage_all(tr, hp, i, lbl_b),
                    params["trunk"],
                    params["head"],
                    inp_b,
                )
                ct_out = (
                    jnp.where(is_last, jnp.zeros_like(out_b), ct_rx)
                    * do_bf.astype(out_b.dtype)
                )
                ct_ce = is_last.astype(jnp.float32) * do_bf
                d_trunk, d_head, d_inp = vjp_fn((ct_out, ct_ce))
                # stage 0's d(input) is the embedding cotangent; everyone
                # else forwards it down the reverse ring
                d_emb_ct = jnp.where(s == 0, d_inp, jnp.zeros_like(d_inp))
                _, evjp = jax.vjp(
                    lambda ep: embed_apply({"params": ep}, tok_b),
                    params["embed"],
                )
                (d_embp,) = evjp(d_emb_ct)
                g_emb = jax.tree.map(jnp.add, g_emb, d_embp)
                g_trunk = jax.tree.map(jnp.add, g_trunk, d_trunk)
                g_head = jax.tree.map(jnp.add, g_head, d_head)
                ct_send = lax.ppermute(d_inp, pipe_axis, rev)
                return (
                    ring, send, ct_send, g_emb, g_trunk, g_head, ce_acc,
                ), None

            act_dtype = jnp.dtype(compute_dtype)
            d_dim = d_model
            zeros_act = lax.pcast(
                jnp.zeros((mb, t_len, d_dim), act_dtype),
                axis_names,
                to="varying",
            )
            g0 = jax.tree.map(
                lambda p: lax.pcast(
                    jnp.zeros_like(p), axis_names, to="varying"
                ),
                params,
            )
            carry0 = (
                lax.pcast(
                    jnp.zeros((ring_k, mb, t_len, d_dim), act_dtype),
                    axis_names,
                    to="varying",
                ),
                zeros_act,
                zeros_act,
                g0["embed"],
                g0["trunk"],
                g0["head"],
                lax.pcast(jnp.float32(0.0), axis_names, to="varying"),
            )
            (_, _, _, g_emb, g_trunk, g_head, ce_total), _ = lax.scan(
                tick, carry0, jnp.arange(m_count + 2 * s_count - 2)
            )
            return hand_epilogue(
                params, opt_state, g_emb, g_trunk, g_head, ce_total,
                v, v0, denom,
            )

        # ---- interleaved 1F1B: v virtual chunks per stage, table-driven ----
        # (pipeline_schedule.py derives per-tick work tables and PROVES the
        # single sticky rx slot per direction suffices; the cyclic ppermute
        # wrap carries a microbatch from chunk c on stage S-1 to chunk c+1
        # on stage 0, so one scan serves all v loops around the ring)
        if schedule == "interleaved":
            from akka_allreduce_tpu.train.pipeline_schedule import (
                interleaved_1f1b_tables,
            )

            tabs = interleaved_1f1b_tables(s_count, m_count, virtual_chunks)
            self.schedule_tables = tabs
            tick_xs = (
                jnp.asarray(tabs.f_micro),
                jnp.asarray(tabs.f_chunk),
                jnp.asarray(tabs.f_arrive),
                jnp.asarray(tabs.b_micro),
                jnp.asarray(tabs.b_chunk),
                jnp.asarray(tabs.b_arrive),
            )
            rk = tabs.ring_k
        v_chunks = virtual_chunks
        chunk_l = layers_per_stage // virtual_chunks

        def chunk_slice(tree, c):
            """This stage's chunk c: rows [c*cl, (c+1)*cl) of its local
            (lps, ...) trunk leaves."""
            return jax.tree.map(
                lambda l: lax.dynamic_slice_in_dim(
                    l, c * chunk_l, chunk_l, axis=0
                ),
                tree,
            )

        def chunk_add(gtree, c, d):
            """Accumulate a chunk's gradient into its slice of the local
            (lps, ...) gradient leaves."""
            return jax.tree.map(
                lambda g, dd: lax.dynamic_update_slice_in_dim(
                    g,
                    lax.dynamic_slice_in_dim(g, c * chunk_l, chunk_l, axis=0)
                    + dd,
                    c * chunk_l,
                    axis=0,
                ),
                gtree,
                d,
            )

        def step_interleaved(params, opt_state, x, y, valid):
            s, v0, v, mb, t_len, is_last, denom = stage_context(x, valid)
            micro_tok = x.reshape(m_count, mb, t_len)
            labels = y.reshape(m_count, mb, t_len)

            def at(row):
                return lax.dynamic_index_in_dim(row, s, 0, keepdims=False)

            def tick(carry, xs):
                fm_row, fc_row, fa_row, bm_row, bc_row, ba_row = xs
                (
                    ring, pend_act, act_rx, pend_ct, ct_rx,
                    g_emb, g_trunk, g_head, ce_acc,
                ) = carry
                # sticky rx: refresh only when the neighbor really sent
                act_rx = jnp.where(at(fa_row), pend_act, act_rx)
                ct_rx = jnp.where(at(ba_row), pend_ct, ct_rx)

                # ---- forward work item ----
                fm, fc_ = at(fm_row), at(fc_row)
                do_f = fm >= 0
                fmc = jnp.clip(fm, 0, m_count - 1)
                tok_f = lax.dynamic_index_in_dim(
                    micro_tok, fmc, 0, keepdims=False
                )
                lbl_f = lax.dynamic_index_in_dim(
                    labels, fmc, 0, keepdims=False
                )
                emb_f = embed_apply({"params": params["embed"]}, tok_f)
                entry = (s == 0) & (fc_ == 0)  # a fresh micro enters here
                inp = jnp.where(entry, emb_f, act_rx)
                slot_f = jnp.mod(fmc, rk)
                prev = lax.dynamic_slice(
                    ring, (fc_, slot_f, 0, 0, 0), (1, 1) + ring.shape[2:]
                )[0, 0]
                ring = lax.dynamic_update_slice(
                    ring,
                    jnp.where(do_f, inp, prev)[None, None],
                    (fc_, slot_f, 0, 0, 0),
                )
                out_f, ce_f = stage_all(
                    chunk_slice(params["trunk"], fc_), params["head"],
                    inp, lbl_f,
                )
                pend_act = lax.ppermute(out_f, pipe_axis, fwd)
                head_site = is_last & (fc_ == v_chunks - 1)
                ce_acc = ce_acc + ce_f * (head_site & do_f).astype(
                    jnp.float32
                )

                # ---- backward work item ----
                bm, bc_ = at(bm_row), at(bc_row)
                do_b = bm >= 0
                do_bf = do_b.astype(jnp.float32)
                bmc = jnp.clip(bm, 0, m_count - 1)
                inp_b = lax.dynamic_slice(
                    ring,
                    (bc_, jnp.mod(bmc, rk), 0, 0, 0),
                    (1, 1) + ring.shape[2:],
                )[0, 0]
                tok_b = lax.dynamic_index_in_dim(
                    micro_tok, bmc, 0, keepdims=False
                )
                lbl_b = lax.dynamic_index_in_dim(
                    labels, bmc, 0, keepdims=False
                )
                (out_b, _), vjp_fn = jax.vjp(
                    lambda tr, hp, i: stage_all(tr, hp, i, lbl_b),
                    chunk_slice(params["trunk"], bc_),
                    params["head"],
                    inp_b,
                )
                head_site_b = is_last & (bc_ == v_chunks - 1)
                ct_out = (
                    jnp.where(head_site_b, jnp.zeros_like(out_b), ct_rx)
                    * do_bf.astype(out_b.dtype)
                )
                ct_ce = head_site_b.astype(jnp.float32) * do_bf
                d_chunk, d_head, d_inp = vjp_fn((ct_out, ct_ce))
                g_trunk = chunk_add(g_trunk, bc_, d_chunk)
                g_head = jax.tree.map(jnp.add, g_head, d_head)
                # the cotangent leaves the pipeline where the micro entered
                exit_site = (s == 0) & (bc_ == 0)
                d_emb_ct = jnp.where(
                    exit_site, d_inp, jnp.zeros_like(d_inp)
                )
                _, evjp = jax.vjp(
                    lambda ep: embed_apply({"params": ep}, tok_b),
                    params["embed"],
                )
                (d_embp,) = evjp(d_emb_ct)
                g_emb = jax.tree.map(jnp.add, g_emb, d_embp)
                pend_ct = lax.ppermute(d_inp, pipe_axis, rev)
                return (
                    ring, pend_act, act_rx, pend_ct, ct_rx,
                    g_emb, g_trunk, g_head, ce_acc,
                ), None

            act_dtype = jnp.dtype(compute_dtype)
            vary = lambda z: lax.pcast(z, axis_names, to="varying")  # noqa: E731
            zeros_act = vary(jnp.zeros((mb, t_len, d_model), act_dtype))
            g0 = jax.tree.map(
                lambda p: vary(jnp.zeros_like(p)), params
            )
            carry0 = (
                vary(
                    jnp.zeros(
                        (v_chunks, rk, mb, t_len, d_model), act_dtype
                    )
                ),
                zeros_act, zeros_act, zeros_act, zeros_act,
                g0["embed"], g0["trunk"], g0["head"],
                vary(jnp.float32(0.0)),
            )
            (*_, g_emb, g_trunk, g_head, ce_total), _ = lax.scan(
                tick, carry0, tick_xs
            )
            return hand_epilogue(
                params, opt_state, g_emb, g_trunk, g_head, ce_total,
                v, v0, denom,
            )

        batch_spec = P(self.data_axis)
        self._data_sharding = NamedSharding(mesh, batch_spec)
        self._valid_sharding = NamedSharding(mesh, P(self.data_axis))
        from akka_allreduce_tpu.ops.local_attention import flash_vma_relax

        # each stage runs FULL-sequence local attention, so the flash
        # kernel can dispatch at kernel-friendly shapes; its outputs carry
        # no vma annotation (same gate as LongContext/MoE); the 1f1b
        # schedule's hand-rolled ppermute plumbing also erases vma (same
        # caveat as the comm layer's rings — the GPipe-equivalence test is
        # the oracle)
        self._check_vma = (
            not overlap
            and compress != "int8"
            and schedule not in ("1f1b", "interleaved")
            and not flash_vma_relax(seq_len, d_model // n_heads)
        )
        step_fns = {
            "gpipe": step,
            "1f1b": step_1f1b,
            "interleaved": step_interleaved,
        }
        mapped = jax.shard_map(
            step_fns[schedule],
            mesh=mesh,
            in_specs=(
                self._param_specs,
                self._opt_specs,
                batch_spec,
                batch_spec,
                P(self.data_axis),
            ),
            out_specs=(self._param_specs, self._opt_specs, P(), P()),
            # off under overlap (custom_vjp erases vma) or a flash
            # dispatch (kernel outputs carry none) — see _check_vma above
            check_vma=self._check_vma,
        )
        self._step = jax.jit(mapped, donate_argnums=(0, 1))
        # reused by train_chain's on-device loop (any schedule)
        self._raw_step = step_fns[schedule]
        self._replicated = NamedSharding(mesh, P())
        self._chains: dict = {}

    # -- stepping ------------------------------------------------------------

    def train_step(
        self,
        tokens: np.ndarray,
        labels: np.ndarray,
        valid: Sequence[float] | None = None,
    ) -> PipelineStepMetrics:
        """One step on a GLOBAL (batch, seq_len) token array; batch divisible
        by dp * microbatches."""
        per_step = self.dp * self.microbatches
        if (
            self._data_sharding.is_fully_addressable
            and tokens.shape[0] % per_step
        ):
            # pod runtime: callers pass HOST-LOCAL rows (place_tokens' seam)
            raise ValueError(
                f"global batch {tokens.shape[0]} not divisible by "
                f"dp*microbatches={per_step}"
            )
        if tokens.shape[1] != self.seq_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} != {self.seq_len}"
            )
        from akka_allreduce_tpu.train.trainer import (
            normalize_valid,
            place_mask,
            place_tokens,
        )

        valid_arr = normalize_valid(valid, self.dp)
        xd, yd = place_tokens(
            tokens, labels, self._data_sharding,
            seq_len=self.seq_len, dp=1,  # dp*microbatches checked above
        )
        vd = place_mask(valid_arr, self._valid_sharding)
        self.params, self.opt_state, loss, cnt = self._step(
            self.params, self.opt_state, xd, yd, vd
        )
        self.step_num += 1
        return PipelineStepMetrics(
            step=self.step_num, loss=float(loss), contributors=float(cnt)
        )

    def train(self, batches) -> list[PipelineStepMetrics]:
        return [self.train_step(x, y) for x, y in batches]

    # -- on-device training chain (no host I/O per step) ---------------------

    def _build_chain(self, sampler, steps: int, rows_per_replica: int):
        raw_step = self._raw_step
        data_axis = self.data_axis

        def chain(params, opt_state, key, valid):
            # one stream per DP replica row; all pipe stages of a row fold
            # the same data coordinate, so they agree on the row's tokens
            # (stage 0 injects, the last stage reads labels)
            rkey = jax.random.fold_in(key, lax.axis_index(data_axis))

            def body(carry, i):
                p, o = carry
                k = jax.random.fold_in(rkey, i)
                x, y = sampler(k, rows_per_replica)
                p, o, loss, cnt = raw_step(p, o, x, y, valid)
                return (p, o), (loss, cnt)

            (params, opt_state), (losses, cnts) = lax.scan(
                body, (params, opt_state), jnp.arange(steps)
            )
            return params, opt_state, losses, cnts

        mapped = jax.shard_map(
            chain,
            mesh=self.mesh,
            in_specs=(
                self._param_specs,
                self._opt_specs,
                P(),
                P(self.data_axis),
            ),
            out_specs=(self._param_specs, self._opt_specs, P(), P()),
            # same vma caveats as the step's shard_map (overlap / flash)
            check_vma=self._check_vma,
        )
        return jax.jit(mapped, donate_argnums=(0, 1))

    def train_chain(
        self,
        sampler,
        steps: int,
        rows_per_replica: int,
        *,
        valid: Sequence[float] | None = None,
        seed: int = 0,
    ) -> list[PipelineStepMetrics]:
        """Run ``steps`` DP x PP steps entirely on device in ONE dispatch
        (``rows_per_replica`` must divide by ``microbatches``)."""
        if rows_per_replica % self.microbatches:
            raise ValueError(
                f"rows_per_replica {rows_per_replica} not divisible by "
                f"{self.microbatches} microbatches"
            )
        from akka_allreduce_tpu.train.trainer import run_chain_cached

        losses, cnts = run_chain_cached(
            self,
            sampler,
            steps,
            rows_per_replica,
            lambda: self._build_chain(sampler, steps, rows_per_replica),
            valid,
            self.dp,
            self._valid_sharding,
            seed,
        )
        out = []
        for loss, cnt in zip(losses, cnts):
            self.step_num += 1
            out.append(
                PipelineStepMetrics(
                    step=self.step_num, loss=float(loss), contributors=float(cnt)
                )
            )
        return out

    # -- checkpoint seam: logical layer order, schedule-portable ------------

    @staticmethod
    def _is_params_container(t) -> bool:
        """A dict mirroring the params layout (optax moments do)."""
        return isinstance(t, dict) and "trunk" in t

    def _map_trunk_order(self, tree, order):
        """Reindex every trunk leaf's layer dim by ``order`` (host-side
        numpy take), for params AND optax moment containers. Identity
        permutation (gpipe/1f1b) is a no-op."""
        if np.array_equal(order, np.arange(len(order))):
            return tree

        def reorder(container):
            out = dict(container)
            out["trunk"] = jax.tree.map(
                lambda l: np.asarray(l)[order], container["trunk"]
            )
            return out

        return jax.tree.map(
            lambda t: reorder(t) if self._is_params_container(t) else t,
            tree,
            is_leaf=self._is_params_container,
        )

    def checkpoint_capture(self) -> dict:
        """Shard-local device state for the async checkpoint path: trunk
        leaves stage-sharded, still on device. The async checkpointer
        copies these HBM-to-HBM and drains them to host in the background
        (VERDICT r4 #1); :meth:`checkpoint_assemble` un-permutes on the
        writer thread."""
        return {"params": self.params, "opt_state": self.opt_state}

    def checkpoint_assemble(self, host: dict) -> dict:
        """Pure-host (numpy) re-order of a captured tree into LOGICAL
        layer order. Runs on the checkpoint writer thread — must not touch
        a device."""
        return self._map_trunk_order(
            {"params": host["params"], "opt_state": host["opt_state"]},
            self._layer_perm_inv,
        )

    def checkpoint_state(self) -> dict:
        """Serialize with trunk leaves in LOGICAL layer order, so a
        checkpoint written under any schedule (gpipe / 1f1b / interleaved,
        any virtual_chunks) restores under any other — the device-storage
        permutation never leaks into the format. Synchronous — the async
        checkpointer uses capture/assemble directly."""
        host = jax.tree.map(lambda x: np.asarray(x), self.checkpoint_capture())
        return self.checkpoint_assemble(host)

    def checkpoint_template(self) -> dict:
        """ShapeDtypeStruct twin (reordering preserves shapes/dtypes)."""
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l), jnp.asarray(l).dtype),
            {"params": self.params, "opt_state": self.opt_state},
        )

    def restore_checkpoint_state(self, state: dict) -> None:
        stored = self._map_trunk_order(
            {"params": state["params"], "opt_state": state["opt_state"]},
            self._layer_perm,
        )
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        place = lambda t, specs: jax.device_put(  # noqa: E731
            t,
            jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), specs, is_leaf=is_spec
            ),
        )
        self.params = place(stored["params"], self._param_specs)
        self.opt_state = place(stored["opt_state"], self._opt_specs)

    def logical_params(self) -> dict:
        """Params with trunk leaves in LOGICAL layer order (host arrays).

        The interleaved schedule stores the trunk in device-traversal
        order (stage-major chunks — see the stacking comment in __init__);
        external views un-permute so cross-schedule comparisons and
        checkpoints see the same model regardless of schedule."""
        host = jax.tree.map(lambda l: np.asarray(l), self.params)
        return self._map_trunk_order(host, self._layer_perm_inv)

    def get_flat_params(self) -> np.ndarray:
        from akka_allreduce_tpu.binder.api import flatten_pytree

        return flatten_pytree(self.logical_params())[0]

    def set_flat_params(self, vec: np.ndarray) -> None:
        """Inverse of :meth:`get_flat_params` (the binder's deposit seam):
        a flat LOGICAL-order vector unflattens into the params tree, the
        trunk re-permutes into this schedule's device-storage order, and
        the leaves re-place onto the current mesh. Optimizer state is
        untouched — the elastic-averaging pull adjusts weights only,
        exactly like ``DPTrainer.set_flat_params``."""
        from jax.flatten_util import ravel_pytree

        host = self.logical_params()
        flat, unravel = ravel_pytree(host)
        if vec.shape != flat.shape:
            raise ValueError(
                f"expected flat params of shape {flat.shape}, got {vec.shape}"
            )
        logical = unravel(jnp.asarray(vec, jnp.float32))
        stored = self._map_trunk_order(
            jax.tree.map(np.asarray, logical), self._layer_perm
        )
        is_spec = lambda x: isinstance(x, P)  # noqa: E731
        self.params = jax.device_put(
            stored,
            jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                self._param_specs,
                is_leaf=is_spec,
            ),
        )
