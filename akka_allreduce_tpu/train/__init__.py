"""Data-parallel training on the ICI data plane (SURVEY.md §8.1 step 4)."""

from akka_allreduce_tpu.train.trainer import DPTrainer, TrainStepMetrics  # noqa: F401
from akka_allreduce_tpu.train.checkpoint import (  # noqa: F401
    AsyncDeltaCheckpointer,
    AsyncTrainerCheckpointer,
    DeltaCheckpointer,
    Snapshot,
    TrainerCheckpointer,
)
from akka_allreduce_tpu.train.cluster import ElasticClusterNode  # noqa: F401
from akka_allreduce_tpu.train.zero1 import Zero1DPTrainer  # noqa: F401
from akka_allreduce_tpu.train.fsdp import FSDPLMTrainer  # noqa: F401
from akka_allreduce_tpu.train.elastic import (  # noqa: F401
    ElasticDPTrainer,
    ElasticLongContextTrainer,
    ElasticMoETrainer,
    ElasticPipelineTrainer,
    ElasticTrainer,
)
from akka_allreduce_tpu.train.long_context import (  # noqa: F401
    LongContextStepMetrics,
    LongContextTrainer,
)
from akka_allreduce_tpu.train.moe import (  # noqa: F401
    MoEStepMetrics,
    MoETrainer,
)
from akka_allreduce_tpu.train.pipeline import (  # noqa: F401
    PipelineLMTrainer,
    PipelineStepMetrics,
)
