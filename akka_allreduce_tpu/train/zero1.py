"""ZeRO-1 data-parallel trainer: optimizer state sharded across the mesh.

Plain DP replicates params AND optimizer state on every device; for Adam that
is 2 extra full copies of the model per device. ZeRO stage 1 keeps params
replicated (forward/backward unchanged) but gives each device only its 1/n
slice of the optimizer state:

    grads --masked reduce-scatter--> my grad shard
    my (param shard, opt shard) --optimizer--> updated param shard
    updated shards --all-gather--> full params on every device

The reduce-scatter + all-gather pair moves exactly the same bytes as the
plain path's all-reduce (an all-reduce IS reduce-scatter + all-gather), so
communication cost is unchanged while optimizer memory drops by n. The
threshold-contribution semantics are preserved: gradients are v-masked before
the reduce-scatter and divided by the contributor count after, exactly
``comm.allreduce.masked_psum``'s math on each shard.

Numerically identical to ``DPTrainer`` with the same optimizer (verified in
tests/test_zero1.py) — except under ``compress="bf16"``, which runs the
gradient reduce-scatter in bfloat16 on the wire (half the ICI bytes; weights
and their all_gather stay float32), trading bit-identity for bandwidth.
``error_feedback=True`` composes with it (DPTrainer's EF contract: a masked
device banks its whole gradient); the residual is purely local here, so EF
adds no collective.
Checkpointing goes through ``TrainerCheckpointer``'s trainer-defined protocol
(``checkpoint_state``/``restore_checkpoint_state``): the flat weight vector
and optimizer moments serialize UNPADDED (mesh-size-independent), so an
n-device checkpoint restores onto any other device count — the moments are
re-padded and re-sharded 1/n' over the new mesh on restore.

Beyond the reference (which has no optimizer-state concept at all); it exists
here because memory per chip is the binding constraint the framework is built
around (HBM section of the design notes).
"""

from __future__ import annotations

import logging
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.train.trainer import (
    TrainStepMetrics,
    default_classification_loss,
    normalize_valid,
    place_batch,
    place_mask,
)

_log = logging.getLogger(__name__)


class Zero1DPTrainer:
    """DP trainer with ZeRO-1 sharded optimizer state.

    Same constructor shape as ``DPTrainer``; only a single flat mesh axis is
    supported (the optimizer shard axis).
    """

    def __init__(
        self,
        model,
        mesh: Mesh,
        example_input: np.ndarray,
        *,
        optimizer: optax.GradientTransformation | None = None,
        learning_rate: float = 0.1,
        loss_fn: Callable | None = None,
        seed: int = 0,
        compress: str | None = None,
        error_feedback: bool = False,
    ) -> None:
        if len(mesh.axis_names) != 1:
            raise ValueError(
                f"zero-1 shards over ONE mesh axis, got {mesh.axis_names}"
            )
        if compress not in (None, "bf16"):
            raise ValueError(
                f"compress must be None or 'bf16', got {compress!r}"
            )
        if error_feedback and compress != "bf16":
            raise ValueError(
                "error_feedback requires compress='bf16' (same contract as "
                "DPTrainer: lossless sync has no residual to carry)"
            )
        # informational only: the jitted step closes over the constructor
        # value — mutating this attribute after construction has no effect
        self.compress = compress
        # NOT merely informational: dispatches train_step and the
        # checkpoint protocol — construct a new trainer to change it
        self.error_feedback = error_feedback
        self.model = model
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_devices = int(mesh.shape[self.axis])
        self.data_shards = self.n_devices
        self.tx = optimizer or optax.adam(learning_rate)
        self._loss = loss_fn or default_classification_loss()

        params = model.init(jax.random.PRNGKey(seed), jnp.asarray(example_input))
        flat, self._unravel = ravel_pytree(params)
        self.param_count = int(flat.shape[0])
        n = self.n_devices
        self._shard_size = -(-self.param_count // n)
        self._padded = self._shard_size * n
        self._data_sharding = NamedSharding(mesh, P(self.axis))
        self._replicated = NamedSharding(mesh, P())
        self.flat_params = jax.device_put(
            jnp.pad(flat, (0, self._padded - self.param_count)),
            self._replicated,
        )

        # optimizer state: one 1/n shard per device. Init states of standard
        # transforms depend on shapes only (zeros / counters), so building
        # the LOCAL state from a zero shard and tiling it sharded is exact.
        local0 = self.tx.init(jnp.zeros((self._shard_size,), jnp.float32))

        def _globalize(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.ndim == 0:  # step counters etc: replicate
                return jax.device_put(leaf, self._replicated)
            return jax.device_put(
                jnp.tile(leaf, (n,) + (1,) * (leaf.ndim - 1)),
                NamedSharding(mesh, P(self.axis)),
            )

        self.opt_state = jax.tree.map(_globalize, local0)
        self._opt_specs = jax.tree.map(
            lambda leaf: P() if jnp.asarray(leaf).ndim == 0 else P(self.axis),
            local0,
        )
        self.step_num = 0

        axis = self.axis
        shard = self._shard_size
        count = self.param_count
        unravel = self._unravel
        model_apply = model.apply
        loss_impl = self._loss
        tx = self.tx

        def compute(flat_params, opt_state, ef, x, y, valid):
            v = valid.reshape(())
            contributors = lax.psum(v, axis)
            denom = jnp.maximum(contributors, 1.0)
            # forward/backward on the full (replicated) params, grads LOCAL
            full = lax.pcast(
                flat_params.reshape(-1)[:count], axis, to="varying"
            )

            def local_loss(flat_local):
                logits = model_apply(unravel(flat_local), x)
                return loss_impl(logits, y)

            loss, gflat = jax.value_and_grad(local_loss)(full)
            gpad = jnp.pad(gflat, (0, shard * lax.axis_size(axis) - count))
            # masked reduce-scatter: my shard of sum_d(v_d * g_d) — in bf16
            # on the wire when compressing (weights all_gather stays f32:
            # compression here is a GRADIENT trade, not a weight truncation)
            if compress == "bf16":
                if ef is not None:
                    # EF-SGD over the reduce-scatter (DPTrainer contract:
                    # c = g + e; send cast(c·v); e' = c - sent). A masked
                    # device sends nothing, so its WHOLE contribution banks
                    # in e'. The residual is purely LOCAL — each device
                    # knows exactly what the cast withheld — so EF costs no
                    # extra collective here.
                    c = gpad.reshape(-1) + ef.reshape(-1)
                    sent16 = (c * v).astype(jnp.bfloat16)
                    new_ef = (c - sent16.astype(jnp.float32)).reshape(ef.shape)
                    wire = sent16
                else:
                    new_ef = None
                    wire = (gpad * v).astype(jnp.bfloat16)
                gshard = lax.psum_scatter(
                    wire, axis, tiled=True
                ).astype(jnp.float32) / denom
            else:
                new_ef = None
                gshard = lax.psum_scatter(gpad * v, axis, tiled=True) / denom
            # my param shard + my optimizer shard -> updated shard
            my = lax.axis_index(axis)
            pshard = lax.dynamic_slice_in_dim(
                flat_params.reshape(-1), my * shard, shard
            )
            updates, new_opt = tx.update(gshard, opt_state, pshard)
            new_shard = optax.apply_updates(pshard, updates)
            # all-gather the updated shards back to full replicated params
            new_flat = lax.all_gather(new_shard, axis, tiled=True)
            loss_avg = lax.psum(loss * v, axis) / denom
            if ef is None:
                return new_flat, new_opt, loss_avg, contributors
            return new_flat, new_opt, new_ef, loss_avg, contributors

        def step(flat_params, opt_state, x, y, valid):
            return compute(flat_params, opt_state, None, x, y, valid)

        data_spec = P(axis)
        self._step = jax.jit(
            jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(P(), self._opt_specs, data_spec, data_spec, data_spec),
                out_specs=(P(), self._opt_specs, P(), P()),
                # the tiled all_gather DOES produce a replicated result, but
                # the static varying-axes check cannot prove it (same caveat
                # as the ring schedules); the DPTrainer-equivalence tests are
                # the oracle
                check_vma=False,
            ),
            donate_argnums=(0, 1),
        )
        if error_feedback:
            # per-device residual of the compressed reduce-scatter, padded
            # to the shard geometry (same layout as the wire vector);
            # materialized ON DEVICE — at ZeRO scale the global buffer is
            # n x model-size, far too big to stream from host as zeros
            self._ef = jax.jit(
                lambda: jnp.zeros((n, self._padded), jnp.float32),
                out_shardings=NamedSharding(mesh, P(axis)),
            )()

            def step_ef(flat_params, opt_state, ef, x, y, valid):
                return compute(flat_params, opt_state, ef, x, y, valid)

            self._step_ef = jax.jit(
                jax.shard_map(
                    step_ef,
                    mesh=mesh,
                    in_specs=(
                        P(), self._opt_specs, data_spec, data_spec,
                        data_spec, data_spec,
                    ),
                    out_specs=(P(), self._opt_specs, data_spec, P(), P()),
                    check_vma=False,
                ),
                donate_argnums=(0, 1, 2),
            )

        def eval_correct(flat_params, x, y):
            logits = model_apply(unravel(flat_params.reshape(-1)[:count]), x)
            hits = jnp.sum(jnp.argmax(logits, -1) == y)
            return lax.psum(hits, axis)

        self._eval = jax.jit(
            jax.shard_map(
                eval_correct,
                mesh=mesh,
                in_specs=(P(), data_spec, data_spec),
                out_specs=P(),
            )
        )

    # -- params as pytree / flat buffer (binder + checkpoint seam) ------------

    @property
    def params(self):
        return self._unravel(
            jnp.asarray(self.flat_params)[: self.param_count]
        )

    def get_flat_params(self) -> np.ndarray:
        return np.asarray(
            jax.device_get(self.flat_params)[: self.param_count],
            dtype=np.float32,
        )

    def set_flat_params(self, vec: np.ndarray) -> None:
        vec = jnp.asarray(vec, jnp.float32)
        if vec.shape != (self.param_count,):
            raise ValueError(
                f"expected flat params of shape ({self.param_count},), "
                f"got {vec.shape}"
            )
        self.flat_params = jax.device_put(
            jnp.pad(vec, (0, self._padded - self.param_count)),
            self._replicated,
        )

    # -- checkpoint seam (TrainerCheckpointer's trainer-defined protocol) ----

    #: serialized-format version: v2 = unpadded mesh-size-independent layout
    #: with an always-present ef_sum (round-1 wrote padded per-mesh leaves
    #: and no version key — restore identifies those explicitly)
    _CKPT_FORMAT_VERSION = 2
    #: template keys TrainerCheckpointer may drop when an OLDER checkpoint
    #: lacks them (restore_checkpoint_state handles their absence)
    checkpoint_optional_keys = frozenset({"format_version", "ef_sum"})

    def checkpoint_capture(self) -> dict:
        """Shard-local device state for the async checkpoint path: the
        replicated flat weight vector, the 1/n-sharded optimizer moments,
        and (when enabled) the per-device EF residual — all still on
        device. The async checkpointer copies these HBM-to-HBM and drains
        them to host in the background (VERDICT r4 #1);
        :meth:`checkpoint_assemble` unpads/serializes on the writer
        thread."""
        cap = {"flat_params": self.flat_params, "opt_state": self.opt_state}
        if self.error_feedback:
            cap["ef"] = self._ef
        return cap

    def checkpoint_assemble(self, host: dict) -> dict:
        """Pure-host (numpy) serialization of a captured tree into the
        mesh-size-independent v2 form (padding tails stripped, EF collapsed
        to its device sum). Runs on the checkpoint writer thread — must not
        touch a device."""
        count = self.param_count

        def unpad(leaf):
            arr = np.asarray(leaf)
            if arr.ndim == 0:  # step counters etc.
                return arr
            return arr.reshape(-1)[:count]

        state = {
            "format_version": np.asarray(
                self._CKPT_FORMAT_VERSION, np.int32
            ),
            "flat_params": np.asarray(
                host["flat_params"], np.float32
            ).reshape(-1)[:count],
            "opt_state": jax.tree.map(unpad, host["opt_state"]),
        }
        if "ef" in host:
            # mesh-size-independent form: the SUM over devices is what the
            # collective is still owed; restore splits it evenly (same
            # cross-mesh strategy as checkpoint._restore_ef)
            state["ef_sum"] = np.asarray(host["ef"], np.float32).sum(axis=0)[
                :count
            ]
        else:
            # ALWAYS present so the tree structure is EF-independent: an
            # EF-written checkpoint restores into a non-EF trainer and vice
            # versa without an Orbax structure mismatch (ADVICE r2); a zero
            # residual is exactly "nothing withheld"
            state["ef_sum"] = np.zeros(count, np.float32)
        return state

    def checkpoint_state(self) -> dict:
        """ZeRO-1 state doesn't fit the params/opt_state pytree shape the
        default checkpoint path assumes (weights are one padded flat vector,
        optimizer moments are 1/n shards): serialize it explicitly.

        The serialized form is mesh-size-INDEPENDENT: the mesh-dependent
        padding tails are stripped, so a checkpoint saved on n devices
        restores onto any other device count (moments are per-flat-element
        state laid out exactly like the flat weight vector, so unpad/re-pad
        is exact — gather-then-reshard at checkpoint scale). Checkpoints
        written by the round-1 padded per-mesh format are not loadable.
        Synchronous — the async checkpointer uses capture/assemble
        directly.
        """
        # via host: slicing a P(axis)-sharded array is an ambiguous gather
        # for the sharding typer, and checkpoint-scale gather-to-host is
        # cheap
        host = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), self.checkpoint_capture()
        )
        return self.checkpoint_assemble(host)

    def checkpoint_template(self) -> dict:
        """Abstract (shape/dtype-only) form of :meth:`checkpoint_state` for
        the restore target — no device_get of throwaway freshly-initialized
        state just to build a template."""
        count = self.param_count

        def tmpl(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.ndim == 0:
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
            return jax.ShapeDtypeStruct((count,), leaf.dtype)

        return {
            "format_version": jax.ShapeDtypeStruct((), jnp.int32),
            "flat_params": jax.ShapeDtypeStruct((count,), jnp.float32),
            "opt_state": jax.tree.map(tmpl, self.opt_state),
            # always requested; TrainerCheckpointer drops it (and
            # format_version) from the target when an older checkpoint
            # lacks it — see checkpoint_optional_keys
            "ef_sum": jax.ShapeDtypeStruct((count,), jnp.float32),
        }

    def restore_checkpoint_state(self, state: dict) -> None:
        """Re-place restored (unpadded) state on this trainer's mesh: flat
        weights re-padded and replicated, optimizer moments re-padded and
        sharded 1/n over THIS mesh (scalar counters replicated) — the mesh
        size at save time is irrelevant."""
        from akka_allreduce_tpu.train.checkpoint import place_on

        version = int(np.asarray(state.pop("format_version", 2)))
        if version > self._CKPT_FORMAT_VERSION:
            raise ValueError(
                f"ZeRO-1 checkpoint format v{version} is newer than this "
                f"build's v{self._CKPT_FORMAT_VERSION}; upgrade the package "
                "to restore it"
            )
        count = self.param_count
        pad = self._padded - count
        self.set_flat_params(np.asarray(state["flat_params"]))

        def reshard(leaf, spec):
            leaf = jnp.asarray(leaf)
            if leaf.ndim == 0:
                return place_on(leaf, self._replicated)
            if leaf.shape != (count,):
                raise ValueError(
                    f"optimizer leaf shape {leaf.shape} != ({count},): "
                    "restore into a trainer with the same model"
                )
            return place_on(
                jnp.pad(leaf, (0, pad)), NamedSharding(self.mesh, spec)
            )

        self.opt_state = jax.tree.map(
            reshard, state["opt_state"], self._opt_specs
        )
        if self.error_feedback:
            if "ef_sum" in state:
                ef_sum = np.asarray(state["ef_sum"], np.float32)
                per = np.tile(ef_sum / self.n_devices, (self.n_devices, 1))
                per = np.pad(per, ((0, 0), (0, pad)))
                self._ef = jax.device_put(
                    per, NamedSharding(self.mesh, P(self.axis))
                )
            else:
                # an old checkpoint with no residual key: a stale live
                # residual would inject the PREVIOUS run's withheld
                # gradients into this one — reset
                self._ef = jax.jit(
                    lambda: jnp.zeros_like(self._ef),
                    out_shardings=NamedSharding(self.mesh, P(self.axis)),
                )()
        elif "ef_sum" in state and np.any(np.asarray(state["ef_sum"])):
            _log.warning(
                "checkpoint carries a NONZERO error-feedback residual but "
                "this trainer has error_feedback off: the withheld gradient "
                "mass is dropped (enable error_feedback to apply it)"
            )

    # -- stepping --------------------------------------------------------------

    def _place_batch(self, x, y):
        return place_batch(x, y, self.n_devices, self._data_sharding)

    def train_step(
        self, x: np.ndarray, y: np.ndarray, valid: Sequence[float] | None = None
    ) -> TrainStepMetrics:
        valid_arr = normalize_valid(valid, self.n_devices)
        xd, yd = self._place_batch(x, y)
        vd = place_mask(valid_arr, self._data_sharding)
        if self.error_feedback:
            (
                self.flat_params, self.opt_state, self._ef, loss, cnt,
            ) = self._step_ef(
                self.flat_params, self.opt_state, self._ef, xd, yd, vd
            )
        else:
            self.flat_params, self.opt_state, loss, cnt = self._step(
                self.flat_params, self.opt_state, xd, yd, vd
            )
        self.step_num += 1
        return TrainStepMetrics(
            step=self.step_num, loss=float(loss), contributors=float(cnt)
        )

    def accuracy(self, x: np.ndarray, y: np.ndarray) -> float:
        xd, yd = self._place_batch(x, y)
        # global hit count over the GLOBAL row count (pod: x is host-local)
        return float(self._eval(self.flat_params, xd, yd)) / xd.shape[0]

    @property
    def optimizer_shard_elems(self) -> int:
        """Per-device element count of each sharded optimizer leaf."""
        return self._shard_size
