"""Static tick tables for pipeline schedules (1F1B and interleaved 1F1B).

The pipeline trainer executes ONE jitted scan over synchronous ticks: per
tick every stage does at most one forward chunk and one backward chunk, and
exactly one activation + one cotangent hop the cyclic ``ppermute``. Under
that model a schedule is fully described by per-tick tables, and the
"conveyor" constraint makes them cheap to derive:

- a forward of (micro m, chunk c) is a TRAIN: once it starts at tick
  ``start_f`` on stage 0 it occupies stage s at ``start_f + s`` (the
  received activation must be consumed the very next tick — there is no
  between-stage buffering beyond the single rx slot);
- a backward is a reverse train: stage s at ``start_b + (S-1) - s``;
- two trains of the same direction collide iff they share a start tick, so
  scheduling = assigning DISTINCT start ticks per direction subject to:
    start_f(m, c)   >= start_f(m, c-1) + S      (chunk chain via the wrap)
    start_b(m, c)   >= start_b(m, c+1) + S      (cotangent chain via wrap)
    start_b(m, c)   >= start_f(m, c) + S - 1    (a stage backs a micro no
                                                 earlier than the tick it
                                                 forwarded it; equality =
                                                 the last stage's same-tick
                                                 fwd+bwd, as in plain 1F1B)
- the greedy below walks ticks and starts a READY backward when one
  exists, else the lowest-(chunk, micro) ready forward — the 1F1B
  discipline that keeps in-flight microbatches (and the input ring) O(S)
  instead of O(M).

With ``v = 1`` the tables reproduce plain 1F1B exactly
(``start_f(m) = m``, ``start_b(m) = m + S - 1``, ``M + 2S - 2`` ticks) —
asserted in tests — so one table-driven tick body serves both schedules.

Interleaving ``v`` chunks per stage shrinks the bubble: each tick's work is
``1/v`` of a stage, so the fill/drain cost (still O(S) ticks) is paid in
chunk units. For S=4, M=8, v=2 the tables give 26 chunk-ticks of makespan
vs plain 1F1B's 14 stage-ticks = 28 chunk-units (a ~7 % smaller step; v=4
gives ≈11 %). These meet the conveyor lower bound
``start_f(0, v-1) + (S-1) + (Mv - 1) + S`` exactly — the remaining gap to
Megatron's asynchronous schedule is inherent to synchronous single-slot
hops (no inter-stage queues), not greedy slack. The usual interleave trade
applies: v× more, smaller, param chunks for the same math.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class TickTables:
    """Per-(tick, stage) work tables; -1 micro = idle slot.

    ``f_arrive``/``b_arrive`` gate the single rx slot per direction: a
    stage overwrites its received-activation (cotangent) slot at tick t
    only when its neighbor really ran a forward (backward) at t-1 — the
    slot is STICKY across schedule gaps, and the builder PROVES no live
    waiting value is ever clobbered (see ``_validate_rx``)."""

    n_ticks: int
    ring_k: int  # pending-input ring slots per chunk (max in-flight + 1)
    f_micro: np.ndarray  # (T, S) int32, -1 = no forward this tick
    f_chunk: np.ndarray  # (T, S) int32
    b_micro: np.ndarray  # (T, S) int32, -1 = no backward this tick
    b_chunk: np.ndarray  # (T, S) int32
    f_arrive: np.ndarray  # (T, S) bool: overwrite act_rx this tick
    b_arrive: np.ndarray  # (T, S) bool: overwrite ct_rx this tick

    @property
    def idle_fraction(self) -> float:
        """Bubble: idle work slots / total, both directions pooled."""
        total = 2 * self.n_ticks * self.f_micro.shape[1]
        busy = int((self.f_micro >= 0).sum() + (self.b_micro >= 0).sum())
        return 1.0 - busy / total


def interleaved_1f1b_tables(
    stages: int, microbatches: int, chunks: int
) -> TickTables:
    """Start-tick assignment by the greedy described in the module doc."""
    s_count, m_count, v = stages, microbatches, chunks
    if s_count < 1 or m_count < 1 or v < 1:
        raise ValueError(f"bad schedule {(stages, microbatches, chunks)}")
    start_f: dict = {}  # (m, c) -> tick
    start_b: dict = {}
    fwd_ticks: set = set()
    bwd_ticks: set = set()
    # Megatron's interleave grouping: microbatches advance in blocks of S —
    # group g runs chunk 0 for micros [gS, (g+1)S), then chunk 1 for the
    # same micros (just arriving back around the ring), and so on; without
    # the grouping every chunk-0 forward runs first and the interleave
    # degenerates to a LONGER plain 1F1B
    fwd_order = sorted(
        ((m, c) for c in range(v) for m in range(m_count)),
        key=lambda mc: (mc[0] // s_count, mc[1], mc[0] % s_count),
    )
    bwd_order = sorted(
        ((m, c) for c in range(v) for m in range(m_count)),
        key=lambda mc: (mc[0] // s_count, v - 1 - mc[1], mc[0] % s_count),
    )

    def f_ready(m, c, t):
        if (m, c) in start_f or t in fwd_ticks:
            return False
        return c == 0 or (
            (m, c - 1) in start_f and t >= start_f[(m, c - 1)] + s_count
        )

    def b_ready(m, c, t):
        if (m, c) in start_b or t in bwd_ticks:
            return False
        if (m, c) not in start_f or t < start_f[(m, c)] + s_count - 1:
            return False
        return c == v - 1 or (
            (m, c + 1) in start_b and t >= start_b[(m, c + 1)] + s_count
        )

    t = 0
    guard = 4 * (m_count * v + 2 * s_count) * max(s_count, 2)
    while len(start_b) < m_count * v:
        if t > guard:  # the greedy always advances; this is a logic fuse
            raise RuntimeError(
                f"schedule did not converge for {(stages, microbatches, chunks)}"
            )
        # 1F1B: drain a backward first (bounds in-flight), then the next
        # forward in interleave order
        for m, c in bwd_order:
            if b_ready(m, c, t):
                start_b[(m, c)] = t
                bwd_ticks.add(t)
                break
        for m, c in fwd_order:
            if f_ready(m, c, t):
                start_f[(m, c)] = t
                fwd_ticks.add(t)
                break
        t += 1

    n_ticks = max(tb + s_count - 1 for tb in start_b.values()) + 1
    shape = (n_ticks, s_count)
    f_micro = np.full(shape, -1, np.int32)
    f_chunk = np.zeros(shape, np.int32)
    b_micro = np.full(shape, -1, np.int32)
    b_chunk = np.zeros(shape, np.int32)
    for (m, c), tf in start_f.items():
        for s in range(s_count):
            f_micro[tf + s, s] = m
            f_chunk[tf + s, s] = c
    for (m, c), tb in start_b.items():
        for s in range(s_count):
            b_micro[tb + (s_count - 1) - s, s] = m
            b_chunk[tb + (s_count - 1) - s, s] = c

    # exact ring bound: a (stage, chunk) slot is LIVE from its fwd tick to
    # its bwd tick (inclusive); the ring keys by micro % ring_k, so verify
    # the chosen size never lets a live slot be overwritten
    max_live = 0
    for s in range(s_count):
        for c in range(v):
            live = 0
            events = []
            for m in range(m_count):
                events.append((start_f[(m, c)] + s, 0, m))
                events.append((start_b[(m, c)] + (s_count - 1) - s, 1, m))
            for _, kind, _ in sorted(events):
                live += 1 if kind == 0 else -1
                max_live = max(max_live, live)
    ring_k = max_live + 1
    for s in range(s_count):
        for c in range(v):
            occupant: dict = {}
            for tick in range(n_ticks):
                if f_micro[tick, s] >= 0 and f_chunk[tick, s] == c:
                    m = int(f_micro[tick, s])
                    slot = m % ring_k
                    if slot in occupant:
                        raise RuntimeError(
                            f"ring collision at stage {s} chunk {c}: "
                            f"micro {m} evicts live {occupant[slot]}"
                        )
                    occupant[slot] = m
                if b_micro[tick, s] >= 0 and b_chunk[tick, s] == c:
                    occupant.pop(int(b_micro[tick, s]) % ring_k, None)

    # rx gating: stage s's fwd slot refreshes at t when stage s-1 ran a
    # real forward at t-1 (cyclic: stage 0 hears S-1); the ct slot when
    # stage s+1 ran a real backward
    f_arrive = np.zeros(shape, bool)
    b_arrive = np.zeros(shape, bool)
    for tick in range(1, n_ticks):
        for s in range(s_count):
            f_arrive[tick, s] = f_micro[tick - 1, (s - 1) % s_count] >= 0
            b_arrive[tick, s] = b_micro[tick - 1, (s + 1) % s_count] >= 0

    _validate_rx(
        s_count, v, start_f, start_b, f_micro, b_micro, n_ticks
    )

    return TickTables(
        n_ticks=n_ticks,
        ring_k=ring_k,
        f_micro=f_micro,
        f_chunk=f_chunk,
        b_micro=b_micro,
        b_chunk=b_chunk,
        f_arrive=f_arrive,
        b_arrive=b_arrive,
    )


def _validate_rx(s_count, v, start_f, start_b, f_micro, b_micro, n_ticks):
    """Prove the single sticky rx slot per direction suffices: between a
    LIVE value's arrival and its consumption, no other real send may land
    on the same stage. Values with no future consumer (chunk v-1's forward
    wrap, chunk 0's backward wrap) are dead on arrival and overwritable."""
    # forward: (m, c)'s output leaves stage S-1 at start_f + S - 1, arrives
    # stage 0 at +S, consumed by (m, c+1)'s forward at start_f(m, c+1);
    # mid-chain hops are consumption-on-arrival by construction (τ = start+s)
    arrivals = []  # (arrive_tick, consume_tick) at stage 0
    for (m, c), tf in start_f.items():
        if c + 1 < v:
            arrivals.append((tf + s_count, start_f[(m, c + 1)]))
    _check_slot(arrivals, [t for t in range(n_ticks) if f_micro[t - 1 if t else 0, s_count - 1] >= 0 and t >= 1], "fwd wrap")
    # backward: (m, c)'s d_inp leaves stage 0 at start_b + S - 1, arrives
    # stage S-1 at +S, consumed by (m, c-1)'s backward at start_b(m, c-1)
    arrivals = []
    for (m, c), tb in start_b.items():
        if c - 1 >= 0:
            arrivals.append((tb + s_count, start_b[(m, c - 1)]))
    _check_slot(arrivals, [t for t in range(n_ticks) if b_micro[t - 1 if t else 0, 0] >= 0 and t >= 1], "bwd wrap")


def _check_slot(arrivals, real_arrival_ticks, label):
    """Every (arrive, consume) window must contain no OTHER real arrival."""
    real = sorted(real_arrival_ticks)
    for arrive, consume in arrivals:
        for t in real:
            if arrive < t <= consume:
                raise RuntimeError(
                    f"rx clobber ({label}): value arriving at {arrive} is "
                    f"overwritten at {t} before its consumption at {consume}"
                )
