"""Elastic DP training: the end-to-end dropout / late-joiner recovery of
BASELINE config 5, composed from the pieces the reference composes
(SURVEY.md §4.5):

    failure detector -> master recomputes membership -> prepare/confirm
    handshake -> rounds resume

with the one structural difference SURVEY.md §8.4 dictates: XLA fixes the
device topology at trace time, so cross-round membership change cannot be a
peer-list swap — it is **snapshot-in-host-RAM -> rebuild the mesh over the
live devices -> restore -> resume**. Within-round straggling still never
triggers this path; it is absorbed by the validity mask (thresholds), exactly
the reference's two-tier design.

Each *node* owns a static set of devices (a TPU host's chips). Heartbeats feed
the phi-accrual detector; a silent node's devices leave the mesh at the next
``poll``; a late joiner's heartbeat brings its devices back in.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Callable, Mapping, Sequence

import jax
import numpy as np

from akka_allreduce_tpu.control.failure import (
    HeartbeatMonitor,
    MembershipEvent,
    PhiAccrualFailureDetector,
)
from akka_allreduce_tpu.parallel.mesh import line_mesh
from akka_allreduce_tpu.train.checkpoint import Snapshot
from akka_allreduce_tpu.train.trainer import DPTrainer, TrainStepMetrics

log = logging.getLogger(__name__)


class ElasticTrainer:
    """ANY trainer re-meshed over the devices of live nodes.

    The generic form of the elastic cycle (VERDICT r3 #3): membership is a
    node -> devices map, the failure detector marks nodes up/down, and on a
    change the CURRENT trainer's state snapshots to host RAM, a NEW trainer
    is built by ``trainer_factory`` over the live devices' mesh, and the
    snapshot restores into it. Trainers with the trainer-defined checkpoint
    protocol (ZeRO-1, FSDP) snapshot through their mesh-size-INDEPENDENT
    serialization, so sharded optimizer/param state survives a device-count
    change; pytree-state trainers (DP/TP/EP/PP) use the replicated-state
    snapshot as before.

    Args:
      trainer_factory: ``mesh -> trainer``; called at construction and on
        every re-mesh with the live devices' mesh.
      devices_by_node: node id -> that node's devices (disjoint). The mesh at
        any moment is the concatenation of live nodes' devices, in node order.
      mesh_factory: devices -> Mesh (default: 1D line; pass grid_mesh for the
        butterfly layout).
      detector: phi-accrual detector (default: Akka-like threshold 8).
      min_nodes: below this many live nodes, ``train_step`` refuses to run
        (the reference's th_allreduce floor applied to membership).
    """

    def __init__(
        self,
        trainer_factory: Callable[[jax.sharding.Mesh], object],
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        mesh_factory: Callable[..., jax.sharding.Mesh] = line_mesh,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not devices_by_node:
            raise ValueError("need at least one node")
        self.trainer_factory = trainer_factory
        self.devices_by_node = {
            int(k): list(v) for k, v in devices_by_node.items()
        }
        self.mesh_factory = mesh_factory
        self.min_nodes = min_nodes
        self.clock = clock
        self.monitor = HeartbeatMonitor(detector)
        self.generation = 0  # the config_id analog: bumps on every re-mesh
        self.remesh_events: list[MembershipEvent] = []

        now = self.clock()
        for node_id in self.devices_by_node:
            self.monitor.heartbeat(node_id, now)
        self.member_nodes: tuple[int, ...] = tuple(self.monitor.members_up)
        self.trainer = self._build_trainer()

    # -- membership ----------------------------------------------------------

    def _live_devices(self) -> list[jax.Device]:
        devs: list[jax.Device] = []
        for node_id in self.member_nodes:
            devs.extend(self.devices_by_node[node_id])
        return devs

    def _build_trainer(self):
        mesh = self.mesh_factory(devices=self._live_devices())
        return self.trainer_factory(mesh)

    def heartbeat(self, node_id: int, now: float | None = None) -> None:
        """Record a node's heartbeat. An unknown node id is a late joiner."""
        if node_id not in self.devices_by_node:
            raise KeyError(
                f"node {node_id} has no device assignment; register it in "
                "devices_by_node before it can join"
            )
        ev = self.monitor.heartbeat(node_id, self.clock() if now is None else now)
        if ev is not None:
            self.remesh_events.append(ev)

    def leave(self, node_id: int, now: float | None = None) -> None:
        ev = self.monitor.leave(node_id, self.clock() if now is None else now)
        if ev is not None:
            self.remesh_events.append(ev)

    def poll(self, now: float | None = None) -> bool:
        """Run failure detection and re-mesh if membership changed.

        Returns True if a re-mesh happened. This is the
        ``PrepareAllreduce -> ConfirmPreparation`` moment of the reference:
        expensive here (re-jit) where the reference's is cheap, which is why
        it only fires on *sustained* failure, never on within-round lag.
        """
        now = self.clock() if now is None else now
        self.remesh_events.extend(self.monitor.poll(now))
        live = tuple(self.monitor.members_up)
        if live == self.member_nodes:
            return False
        if not live:
            raise RuntimeError("all nodes unreachable; cannot re-mesh")
        log.info(
            "re-mesh: members %s -> %s (generation %d -> %d)",
            self.member_nodes,
            live,
            self.generation,
            self.generation + 1,
        )
        snap = Snapshot.capture(self.trainer)
        self.member_nodes = live
        self.generation += 1
        self.trainer = self._build_trainer()
        snap.restore_into(self.trainer)
        return True

    def remesh(self, reason: str = "forced") -> bool:
        """Force a re-mesh with UNCHANGED membership: snapshot -> rebuild
        -> restore, generation bump. The in-process analog of every node
        re-joining a promoted standby master after a leader failover (the
        soak's leader-kill schedule entry): membership SURVIVED — the warm
        standby carried it in the state digest — but the whole cluster
        still re-runs the Prepare handshake under the new leader's epoch,
        which on the XLA side is a full re-jit."""
        log.info(
            "re-mesh (%s): members %s unchanged (generation %d -> %d)",
            reason, self.member_nodes, self.generation, self.generation + 1,
        )
        snap = Snapshot.capture(self.trainer)
        self.generation += 1
        self.trainer = self._build_trainer()
        snap.restore_into(self.trainer)
        return True

    # -- training ------------------------------------------------------------

    @property
    def n_devices(self) -> int:
        return self.trainer.n_devices

    @property
    def n_nodes(self) -> int:
        return len(self.member_nodes)

    def train_step(
        self, x: np.ndarray, y: np.ndarray, valid: Sequence[float] | None = None
    ) -> TrainStepMetrics:
        if self.n_nodes < self.min_nodes:
            raise RuntimeError(
                f"only {self.n_nodes} live nodes < min_nodes={self.min_nodes}"
            )
        return self.trainer.train_step(x, y, valid)

    def get_flat_params(self) -> np.ndarray:
        if hasattr(self.trainer, "get_flat_params"):
            return self.trainer.get_flat_params()
        # FSDP exposes gathered_params() instead of a flat vector
        from akka_allreduce_tpu.binder.api import flatten_pytree

        return flatten_pytree(self.trainer.gathered_params())[0]


def adaptive_parallel_factor(n_devices: int, divides: int) -> int:
    """Largest axis size that divides BOTH the live device count and a
    model-structure count (experts / total layers / sequence length).

    The elastic wrinkle for sharded model structure (VERDICT r3 next-round
    #1): the number of experts, pipeline layers, or sequence positions is
    FIXED by the model, but the mesh axis carrying it must divide the live
    device count, which changes on every re-mesh. The policy here maximizes
    the structure axis (most parallelism over the scarce dimension) subject
    to both divisibilities; the data axis absorbs the rest.
    """
    if n_devices < 1 or divides < 1:
        raise ValueError(f"need positive counts, got {n_devices=}, {divides=}")
    return math.gcd(n_devices, divides)


def _capped_factor(n_devices: int, divides: int, cap: int | None) -> int:
    """adaptive_parallel_factor, optionally capped (a smaller axis keeps
    per-shard work non-trivial — e.g. layers_per_stage >= virtual_chunks,
    or enough local tokens per seq shard)."""
    g = adaptive_parallel_factor(n_devices, divides)
    if cap is None or g <= cap:
        return g
    if cap < 1:
        raise ValueError(f"axis cap must be >= 1, got {cap}")
    return max(f for f in range(1, cap + 1) if g % f == 0)


class ElasticMoETrainer(ElasticTrainer):
    """Elastic expert-parallel training: the (data, expert) mesh re-shapes
    with membership. On every re-mesh the expert axis becomes the largest
    size dividing both ``n_experts`` and the live device count, so the
    SAME experts redistribute over fewer/more devices: expert-sharded
    leaves ((E, ...) stacked, ``ep_param_specs``) snapshot as global host
    arrays and re-place onto the new axis — 2 experts/device at ep=4 can
    become 4/device at ep=2 and back, with routing unchanged (capacity is
    computed per LOCAL tokens, so ample ``capacity_factor`` keeps the step
    partition-independent — the continuation oracle in the tests)."""

    def __init__(
        self,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        n_experts: int = 4,
        max_ep: int | None = None,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        from akka_allreduce_tpu.train.moe import MoETrainer

        def mesh_factory(*, devices):
            n = len(devices)
            ep = _capped_factor(n, n_experts, max_ep)
            return jax.make_mesh(
                (n // ep, ep), ("data", "expert"), devices=devices
            )

        def factory(mesh):
            return MoETrainer(mesh, n_experts=n_experts, **trainer_kwargs)

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )


class ElasticPipelineTrainer(ElasticTrainer):
    """Elastic pipeline-parallel training: the (data, pipe) mesh re-shapes
    with membership. Total trunk depth ``n_layers`` is fixed; on re-mesh
    the stage count becomes the largest size dividing both ``n_layers``
    and the live device count, and ``layers_per_stage`` re-derives as
    ``n_layers // stages`` — the same logical layers re-chunk across a
    different number of stages. State crosses the shape change through the
    trainer's LOGICAL-layer-order checkpoint protocol (the stacked trunk
    is (n_layers, ...) regardless of the stage split, and
    ``restore_checkpoint_state`` applies the NEW trainer's stage
    permutation), which also makes the re-mesh schedule-portable. With
    ``schedule='interleaved'``, ``virtual_chunks`` must divide every
    reachable ``layers_per_stage``; the factory surfaces the trainer's
    ValueError if a membership change breaks that."""

    def __init__(
        self,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        n_layers: int = 2,
        microbatches: int = 2,
        max_pp: int | None = None,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        from akka_allreduce_tpu.train.pipeline import PipelineLMTrainer

        # interleaved needs layers_per_stage divisible by virtual_chunks at
        # EVERY reachable stage count; exactly the stage counts dividing
        # n_layers/virtual satisfy that (lps = virtual * (n_layers/virtual)
        # / pp), so the adaptive factor targets that quotient
        virtual = max(int(trainer_kwargs.get("virtual_chunks", 1)), 1)
        if n_layers % virtual:
            raise ValueError(
                f"{n_layers=} not divisible by virtual_chunks={virtual}"
            )
        pp_divides = n_layers // virtual

        def mesh_factory(*, devices):
            n = len(devices)
            pp = _capped_factor(n, pp_divides, max_pp)
            return jax.make_mesh(
                (n // pp, pp), ("data", "pipe"), devices=devices
            )

        def factory(mesh):
            pp = int(mesh.shape["pipe"])
            return PipelineLMTrainer(
                mesh,
                layers_per_stage=n_layers // pp,
                microbatches=microbatches,
                **trainer_kwargs,
            )

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )


class ElasticLongContextTrainer(ElasticTrainer):
    """Elastic sequence-parallel training: the (data, seq) mesh re-shapes
    with membership. On re-mesh the seq axis becomes the largest size that
    divides both ``seq_len`` and the live device count, capped at
    ``max_sp`` (ring/Ulysses want enough LOCAL tokens per shard to stay
    compute-bound); each replica's sequence re-splits across the new shard
    count. Params are replicated (no TP — tensor-parallel elasticity would
    additionally re-shard heads and is not composed here), so the snapshot
    crosses any shape change; numerics match continuation to ring-reduce
    float tolerance (the blockwise softmax reduces in a different block
    order under a different sp)."""

    def __init__(
        self,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        seq_len: int = 128,
        max_sp: int | None = None,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        from akka_allreduce_tpu.train.long_context import LongContextTrainer

        def mesh_factory(*, devices):
            n = len(devices)
            sp = _capped_factor(n, seq_len, max_sp)
            return jax.make_mesh(
                (n // sp, sp), ("data", "seq"), devices=devices
            )

        def factory(mesh):
            return LongContextTrainer(mesh, seq_len=seq_len, **trainer_kwargs)

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )


class ElasticDPTrainer(ElasticTrainer):
    """DP form of :class:`ElasticTrainer` (the original elastic cycle):
    builds a :class:`DPTrainer` from ``model``/``example_input`` on every
    re-mesh. Kept as the config-5 workhorse; ZeRO-1/FSDP go through
    :class:`ElasticTrainer` with their own factory."""

    def __init__(
        self,
        model,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        example_input: np.ndarray,
        *,
        mesh_factory: Callable[..., jax.sharding.Mesh] = line_mesh,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        example = np.asarray(example_input)

        def factory(mesh):
            return DPTrainer(
                model, mesh, example_input=example, **trainer_kwargs
            )

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )
        self.model = model
        self.example_input = example
