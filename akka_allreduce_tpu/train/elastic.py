"""Elastic DP training: the end-to-end dropout / late-joiner recovery of
BASELINE config 5, composed from the pieces the reference composes
(SURVEY.md §4.5):

    failure detector -> master recomputes membership -> prepare/confirm
    handshake -> rounds resume

with the one structural difference SURVEY.md §8.4 dictates: XLA fixes the
device topology at trace time, so cross-round membership change cannot be a
peer-list swap — it is **snapshot-in-host-RAM -> rebuild the mesh over the
live devices -> restore -> resume**. Within-round straggling still never
triggers this path; it is absorbed by the validity mask (thresholds), exactly
the reference's two-tier design.

Each *node* owns a static set of devices (a TPU host's chips). Heartbeats feed
the phi-accrual detector; a silent node's devices leave the mesh at the next
``poll``; a late joiner's heartbeat brings its devices back in.
"""

from __future__ import annotations

import inspect
import logging
import math
import time
from typing import Callable, Mapping, Sequence

import jax
import numpy as np

from akka_allreduce_tpu.control.adapt import WIRE_TO_COMPRESS
from akka_allreduce_tpu.control.failure import (
    HeartbeatMonitor,
    MembershipEvent,
    PhiAccrualFailureDetector,
)
from akka_allreduce_tpu.obs import flight as _flight
from akka_allreduce_tpu.obs import metrics as _metrics
from akka_allreduce_tpu.parallel.mesh import line_mesh
from akka_allreduce_tpu.train.checkpoint import Snapshot
from akka_allreduce_tpu.train.trainer import DPTrainer, TrainStepMetrics

log = logging.getLogger(__name__)

# elastic.* observability (OBSERVABILITY.md): every snapshot->rebuild->
# restore cycle lands one histogram observation + one per-kind counter +
# one `remesh` flight event; the compress gauge tracks the ICI ladder
_REMESH_SECONDS = _metrics.histogram("elastic.remesh.seconds")
_COMPRESS_LEVEL = _metrics.gauge("elastic.compress_level")

#: trainer ``compress`` mode -> ICI degrade-ladder level (the gauge's
#: unit, mirroring ``adapt.level`` on the host plane)
COMPRESS_LEVELS = {None: 0, "bf16": 1, "int8": 2}

#: sentinel: no compress override in force — rebuilds run the factory at
#: its construction-time mode (``apply_policy_wire("")`` restores this)
_INHERIT = object()


class ElasticTrainer:
    """ANY trainer re-meshed over the devices of live nodes.

    The generic form of the elastic cycle (VERDICT r3 #3): membership is a
    node -> devices map, the failure detector marks nodes up/down, and on a
    change the CURRENT trainer's state snapshots to host RAM, a NEW trainer
    is built by ``trainer_factory`` over the live devices' mesh, and the
    snapshot restores into it. Trainers with the trainer-defined checkpoint
    protocol (ZeRO-1, FSDP) snapshot through their mesh-size-INDEPENDENT
    serialization, so sharded optimizer/param state survives a device-count
    change; pytree-state trainers (DP/TP/EP/PP) use the replicated-state
    snapshot as before.

    Args:
      trainer_factory: ``mesh -> trainer``; called at construction and on
        every re-mesh with the live devices' mesh.
      devices_by_node: node id -> that node's devices (disjoint). The mesh at
        any moment is the concatenation of live nodes' devices, in node order.
      mesh_factory: devices -> Mesh (default: 1D line; pass grid_mesh for the
        butterfly layout).
      detector: phi-accrual detector (default: Akka-like threshold 8).
      min_nodes: below this many live nodes, ``train_step`` refuses to run
        (the reference's th_allreduce floor applied to membership).
      fallback_mesh_factory: devices -> Mesh tried when
        ``trainer_factory`` REFUSES the primary mesh on a re-mesh (raises)
        — the degrade-not-wedge escape hatch (RESILIENCE.md "Tier 7"):
        e.g. a pipeline factory pinned to a fixed stage count falls back
        to the DP-only mesh instead of wedging the elastic cycle. The
        built-in families never need it (their adaptive axes are
        gcd-derived, so every live device count has a valid shape).
    """

    def __init__(
        self,
        trainer_factory: Callable[[jax.sharding.Mesh], object],
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        mesh_factory: Callable[..., jax.sharding.Mesh] = line_mesh,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        fallback_mesh_factory: Callable[..., jax.sharding.Mesh] | None = None,
    ) -> None:
        if not devices_by_node:
            raise ValueError("need at least one node")
        self.trainer_factory = trainer_factory
        self.devices_by_node = {
            int(k): list(v) for k, v in devices_by_node.items()
        }
        self.mesh_factory = mesh_factory
        self.fallback_mesh_factory = fallback_mesh_factory
        self.min_nodes = min_nodes
        self.clock = clock
        self.monitor = HeartbeatMonitor(detector)
        self.generation = 0  # the config_id analog: bumps on every re-mesh
        self.remesh_events: list[MembershipEvent] = []
        # ICI compress override (RESILIENCE.md "Tier 7" — compress follows
        # policy): _INHERIT = run the factory at its construction mode;
        # set_compress/apply_policy_wire swap it and rebuild through the
        # SAME trainer-factory path every re-mesh uses
        self._compress = _INHERIT
        self._factory_takes_compress = (
            "compress" in inspect.signature(trainer_factory).parameters
        )
        # optional mode -> mode map for families whose factory CLAMPS the
        # request (e.g. ZeRO-1 has no int8 ring: int8 -> bf16). Applied
        # BEFORE the changed-mode check, so a stamp the clamp maps onto
        # the current mode does not trigger a no-op factory rebuild.
        self.clamp_compress: Callable[[str | None], str | None] | None = None

        now = self.clock()
        for node_id in self.devices_by_node:
            self.monitor.heartbeat(node_id, now)
        self.member_nodes: tuple[int, ...] = tuple(self.monitor.members_up)
        self.trainer = self._build_trainer()
        # the construction-time mode "" (inherit) restores to
        self._base_compress = getattr(self.trainer, "compress", None)
        _COMPRESS_LEVEL.set(COMPRESS_LEVELS.get(self.compress_mode, 0))

    # -- membership ----------------------------------------------------------

    def _live_devices(self) -> list[jax.Device]:
        devs: list[jax.Device] = []
        for node_id in self.member_nodes:
            devs.extend(self.devices_by_node[node_id])
        return devs

    def _build_trainer(self, mesh_factory=None):
        mesh = (mesh_factory or self.mesh_factory)(
            devices=self._live_devices()
        )
        if self._factory_takes_compress and self._compress is not _INHERIT:
            return self.trainer_factory(mesh, compress=self._compress)
        return self.trainer_factory(mesh)

    def _rebuild(self, kind: str, old_members: tuple[int, ...]) -> None:
        """The elastic cycle's core: snapshot -> rebuild over the CURRENT
        ``member_nodes`` -> restore, transactionally — on a factory
        refusal the fallback mesh is tried (degrade, not wedge), and if
        everything fails ``member_nodes`` reverts so the OLD trainer stays
        usable (its devices may be live; the caller decides what to do
        with the raised error)."""
        t0 = time.perf_counter()
        snap = Snapshot.capture(self.trainer)
        try:
            trainer = self._build_trainer()
        except Exception:
            if self.fallback_mesh_factory is None:
                self.member_nodes = old_members
                raise
            log.warning(
                "re-mesh (%s): factory refused the %d-device mesh; "
                "degrading to the fallback mesh",
                kind, len(self._live_devices()), exc_info=True,
            )
            try:
                trainer = self._build_trainer(self.fallback_mesh_factory)
            except Exception:
                self.member_nodes = old_members
                raise
        try:
            snap.restore_into(trainer)
        except Exception:
            # the old trainer was never touched: keep it, and the old view
            self.member_nodes = old_members
            raise
        self.trainer = trainer
        self.generation += 1
        dt = time.perf_counter() - t0
        _REMESH_SECONDS.observe(dt)
        _metrics.counter(f"elastic.remeshes.{kind}").inc()
        _COMPRESS_LEVEL.set(COMPRESS_LEVELS.get(self.compress_mode, 0))
        _flight.note(
            "remesh",
            cause=kind,
            members_from=list(old_members),
            members_to=list(self.member_nodes),
            generation=self.generation,
            n_devices=self.trainer.n_devices,
            seconds=round(dt, 4),
        )

    def heartbeat(self, node_id: int, now: float | None = None) -> None:
        """Record a node's heartbeat. An unknown node id is a late joiner."""
        if node_id not in self.devices_by_node:
            raise KeyError(
                f"node {node_id} has no device assignment; register it in "
                "devices_by_node before it can join"
            )
        ev = self.monitor.heartbeat(node_id, self.clock() if now is None else now)
        if ev is not None:
            self.remesh_events.append(ev)

    def leave(self, node_id: int, now: float | None = None) -> None:
        ev = self.monitor.leave(node_id, self.clock() if now is None else now)
        if ev is not None:
            self.remesh_events.append(ev)

    def poll(self, now: float | None = None) -> bool:
        """Run failure detection and re-mesh if membership changed.

        Returns True if a re-mesh happened. This is the
        ``PrepareAllreduce -> ConfirmPreparation`` moment of the reference:
        expensive here (re-jit) where the reference's is cheap, which is why
        it only fires on *sustained* failure, never on within-round lag.
        """
        now = self.clock() if now is None else now
        self.remesh_events.extend(self.monitor.poll(now))
        live = tuple(self.monitor.members_up)
        if live == self.member_nodes:
            return False
        if not live:
            raise RuntimeError("all nodes unreachable; cannot re-mesh")
        log.info(
            "re-mesh: members %s -> %s (generation %d -> %d)",
            self.member_nodes,
            live,
            self.generation,
            self.generation + 1,
        )
        old = self.member_nodes
        self.member_nodes = live
        self._rebuild("grow" if len(live) > len(old) else "shrink", old)
        return True

    def apply_membership(
        self, live: Sequence[int], now: float | None = None
    ) -> bool:
        """Re-mesh to an EXTERNALLY-decided membership view (RESILIENCE.md
        "Tier 7"): the TCP cluster's failure detector — phi hub or SWIM
        gossip — already judged who is alive, so the in-process phi
        monitor is bypassed as a *detector* and merely kept coherent (its
        records mirror the applied view, so a later ``poll`` cannot
        re-litigate the verdict). Node ids without a device assignment are
        ignored (a cluster can admit more nodes than this trainer planned
        devices for). Returns True when a re-mesh happened."""
        now = self.clock() if now is None else now
        known = sorted(
            {int(n) for n in live} & set(self.devices_by_node)
        )
        if not known:
            raise RuntimeError(
                f"no live node in {sorted(set(map(int, live)))} has a "
                "device assignment; cannot re-mesh"
            )
        for nid in known:
            ev = self.monitor.heartbeat(nid, now)
            if ev is not None:
                self.remesh_events.append(ev)
        for nid in set(self.member_nodes) - set(known):
            ev = self.monitor.force_unreachable(nid, now)
            if ev is not None:
                self.remesh_events.append(ev)
        target = tuple(known)
        if target == self.member_nodes:
            return False
        old = self.member_nodes
        log.info(
            "re-mesh (membership): members %s -> %s (generation %d -> %d)",
            old, target, self.generation, self.generation + 1,
        )
        self.member_nodes = target
        self._rebuild("grow" if len(target) > len(old) else "shrink", old)
        return True

    def remesh(self, reason: str = "forced") -> bool:
        """Force a re-mesh with UNCHANGED membership: snapshot -> rebuild
        -> restore, generation bump. The in-process analog of every node
        re-joining a promoted standby master after a leader failover (the
        soak's leader-kill schedule entry): membership SURVIVED — the warm
        standby carried it in the state digest — but the whole cluster
        still re-runs the Prepare handshake under the new leader's epoch,
        which on the XLA side is a full re-jit."""
        log.info(
            "re-mesh (%s): members %s unchanged (generation %d -> %d)",
            reason, self.member_nodes, self.generation, self.generation + 1,
        )
        self._rebuild(reason, self.member_nodes)
        return True

    # -- ICI compress follows the RoundPolicy (the adaptive loop's far end) --

    @property
    def compress_mode(self) -> str | None:
        """The LIVE trainer's ICI wire mode (None / "bf16" / "int8")."""
        return getattr(self.trainer, "compress", None)

    def set_compress(self, mode: str | None) -> bool:
        """Switch the trainer's ICI gradient compression by REBUILDING it
        through the trainer factory (snapshot -> factory(mesh,
        compress=mode) -> restore) — a mode change re-jits once, exactly
        like a membership re-mesh, never per step. Error-feedback state
        crosses the rebuild inside the snapshot (``_restore_ef``: the
        residual sum — what the collective is still owed — is preserved);
        a restore OUT of a compressed mode into one without EF drops the
        residual, mirroring the host worker's restore-out-of-int8 rule.
        Returns True when a rebuild happened."""
        return self._set_compress_override(mode)

    def apply_policy_wire(self, wire: str) -> bool:
        """Drive :meth:`set_compress` from a :class:`RoundPolicy` wire
        stamp — the ICI half of the closed adaptive loop: one leader
        controller degrades the host wire (per-frame f16/int8) AND, via
        this seam, the XLA collectives of whatever trainer rides the
        cluster. ``""`` (the default stamp) clears the override, i.e.
        restores the factory's construction-time mode."""
        wire = wire or ""
        if wire == "":
            return self._set_compress_override(_INHERIT)
        if wire not in WIRE_TO_COMPRESS:
            log.warning("unknown policy wire %r: keeping compress mode", wire)
            return False
        return self._set_compress_override(WIRE_TO_COMPRESS[wire])

    def _set_compress_override(self, value) -> bool:
        mode = self._base_compress if value is _INHERIT else value
        if mode not in COMPRESS_LEVELS:
            raise ValueError(
                f"compress must be one of {sorted(COMPRESS_LEVELS, key=str)}, "
                f"got {mode!r}"
            )
        if value is not _INHERIT and self.clamp_compress is not None:
            clamped = self.clamp_compress(mode)
            if clamped != mode:
                log.info("compress %s clamped to %s", mode, clamped)
                mode = value = clamped
        if mode == self.compress_mode:
            self._compress = value  # record intent; nothing to rebuild
            return False
        if not self._factory_takes_compress:
            raise RuntimeError(
                "this trainer_factory does not accept a `compress` kwarg; "
                "a policy-driven mode change has no rebuild path"
            )
        old = self._compress
        self._compress = value
        try:
            self._rebuild("compress", self.member_nodes)
        except Exception:
            self._compress = old
            raise
        log.info(
            "compress level -> %s (generation %d)",
            mode or "full", self.generation,
        )
        return True

    # -- training ------------------------------------------------------------

    @property
    def n_devices(self) -> int:
        return self.trainer.n_devices

    @property
    def n_nodes(self) -> int:
        return len(self.member_nodes)

    @property
    def param_count(self) -> int:
        """Logical model size — invariant across re-meshes by contract
        (what the cluster's ``data_size`` is derived from)."""
        return self.trainer.param_count

    def train_step(
        self, x: np.ndarray, y: np.ndarray, valid: Sequence[float] | None = None
    ) -> TrainStepMetrics:
        if self.n_nodes < self.min_nodes:
            raise RuntimeError(
                f"only {self.n_nodes} live nodes < min_nodes={self.min_nodes}"
            )
        return self.trainer.train_step(x, y, valid)

    def get_flat_params(self) -> np.ndarray:
        if hasattr(self.trainer, "get_flat_params"):
            return self.trainer.get_flat_params()
        # FSDP exposes gathered_params() instead of a flat vector
        from akka_allreduce_tpu.binder.api import flatten_pytree

        return flatten_pytree(self.trainer.gathered_params())[0]

    def set_flat_params(self, vec: np.ndarray) -> None:
        """Binder deposit seam: the elastic-averaging sink writes the
        group average back into whatever trainer is live right now (the
        flat LOGICAL layout is mesh-size-independent, so a deposit is
        valid across re-meshes)."""
        self.trainer.set_flat_params(vec)


def adaptive_parallel_factor(n_devices: int, divides: int) -> int:
    """Largest axis size that divides BOTH the live device count and a
    model-structure count (experts / total layers / sequence length).

    The elastic wrinkle for sharded model structure (VERDICT r3 next-round
    #1): the number of experts, pipeline layers, or sequence positions is
    FIXED by the model, but the mesh axis carrying it must divide the live
    device count, which changes on every re-mesh. The policy here maximizes
    the structure axis (most parallelism over the scarce dimension) subject
    to both divisibilities; the data axis absorbs the rest.
    """
    if n_devices < 1 or divides < 1:
        raise ValueError(f"need positive counts, got {n_devices=}, {divides=}")
    return math.gcd(n_devices, divides)


def _capped_factor(n_devices: int, divides: int, cap: int | None) -> int:
    """adaptive_parallel_factor, optionally capped (a smaller axis keeps
    per-shard work non-trivial — e.g. layers_per_stage >= virtual_chunks,
    or enough local tokens per seq shard)."""
    g = adaptive_parallel_factor(n_devices, divides)
    if cap is None or g <= cap:
        return g
    if cap < 1:
        raise ValueError(f"axis cap must be >= 1, got {cap}")
    return max(f for f in range(1, cap + 1) if g % f == 0)


class ElasticMoETrainer(ElasticTrainer):
    """Elastic expert-parallel training: the (data, expert) mesh re-shapes
    with membership. On every re-mesh the expert axis becomes the largest
    size dividing both ``n_experts`` and the live device count, so the
    SAME experts redistribute over fewer/more devices: expert-sharded
    leaves ((E, ...) stacked, ``ep_param_specs``) snapshot as global host
    arrays and re-place onto the new axis — 2 experts/device at ep=4 can
    become 4/device at ep=2 and back, with routing unchanged (capacity is
    computed per LOCAL tokens, so ample ``capacity_factor`` keeps the step
    partition-independent — the continuation oracle in the tests)."""

    def __init__(
        self,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        n_experts: int = 4,
        max_ep: int | None = None,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        from akka_allreduce_tpu.train.moe import MoETrainer

        def mesh_factory(*, devices):
            n = len(devices)
            ep = _capped_factor(n, n_experts, max_ep)
            return jax.make_mesh(
                (n // ep, ep), ("data", "expert"), devices=devices
            )

        def factory(mesh, compress=_INHERIT):
            kw = dict(trainer_kwargs)
            if compress is not _INHERIT:
                kw["compress"] = compress
            return MoETrainer(mesh, n_experts=n_experts, **kw)

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )


class ElasticPipelineTrainer(ElasticTrainer):
    """Elastic pipeline-parallel training: the (data, pipe) mesh re-shapes
    with membership. Total trunk depth ``n_layers`` is fixed; on re-mesh
    the stage count becomes the largest size dividing both ``n_layers``
    and the live device count, and ``layers_per_stage`` re-derives as
    ``n_layers // stages`` — the same logical layers re-chunk across a
    different number of stages. State crosses the shape change through the
    trainer's LOGICAL-layer-order checkpoint protocol (the stacked trunk
    is (n_layers, ...) regardless of the stage split, and
    ``restore_checkpoint_state`` applies the NEW trainer's stage
    permutation), which also makes the re-mesh schedule-portable. With
    ``schedule='interleaved'``, ``virtual_chunks`` must divide every
    reachable ``layers_per_stage``; the factory surfaces the trainer's
    ValueError if a membership change breaks that."""

    def __init__(
        self,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        n_layers: int = 2,
        microbatches: int = 2,
        max_pp: int | None = None,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        from akka_allreduce_tpu.train.pipeline import PipelineLMTrainer

        # interleaved needs layers_per_stage divisible by virtual_chunks at
        # EVERY reachable stage count; exactly the stage counts dividing
        # n_layers/virtual satisfy that (lps = virtual * (n_layers/virtual)
        # / pp), so the adaptive factor targets that quotient
        virtual = max(int(trainer_kwargs.get("virtual_chunks", 1)), 1)
        if n_layers % virtual:
            raise ValueError(
                f"{n_layers=} not divisible by virtual_chunks={virtual}"
            )
        pp_divides = n_layers // virtual

        def mesh_factory(*, devices):
            n = len(devices)
            pp = _capped_factor(n, pp_divides, max_pp)
            return jax.make_mesh(
                (n // pp, pp), ("data", "pipe"), devices=devices
            )

        def factory(mesh, compress=_INHERIT):
            pp = int(mesh.shape["pipe"])
            kw = dict(trainer_kwargs)
            if compress is not _INHERIT:
                kw["compress"] = compress
            return PipelineLMTrainer(
                mesh,
                layers_per_stage=n_layers // pp,
                microbatches=microbatches,
                **kw,
            )

        def dp_only_mesh(*, devices):
            # one stage's worth (or an otherwise-refused shape) survives:
            # the whole trunk runs on every device, data-parallel only —
            # the restage rule's floor (RESILIENCE.md "Tier 7")
            return jax.make_mesh(
                (len(devices), 1), ("data", "pipe"), devices=devices
            )

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
            fallback_mesh_factory=dp_only_mesh,
        )


class ElasticLongContextTrainer(ElasticTrainer):
    """Elastic sequence-parallel training: the (data, seq) mesh re-shapes
    with membership. On re-mesh the seq axis becomes the largest size that
    divides both ``seq_len`` and the live device count, capped at
    ``max_sp`` (ring/Ulysses want enough LOCAL tokens per shard to stay
    compute-bound); each replica's sequence re-splits across the new shard
    count. Params are replicated (no TP — tensor-parallel elasticity would
    additionally re-shard heads and is not composed here), so the snapshot
    crosses any shape change; numerics match continuation to ring-reduce
    float tolerance (the blockwise softmax reduces in a different block
    order under a different sp)."""

    def __init__(
        self,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        *,
        seq_len: int = 128,
        max_sp: int | None = None,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        from akka_allreduce_tpu.train.long_context import LongContextTrainer

        def mesh_factory(*, devices):
            n = len(devices)
            sp = _capped_factor(n, seq_len, max_sp)
            return jax.make_mesh(
                (n // sp, sp), ("data", "seq"), devices=devices
            )

        def factory(mesh, compress=_INHERIT):
            kw = dict(trainer_kwargs)
            if compress is not _INHERIT:
                kw["compress"] = compress
            return LongContextTrainer(mesh, seq_len=seq_len, **kw)

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )


class ElasticDPTrainer(ElasticTrainer):
    """DP form of :class:`ElasticTrainer` (the original elastic cycle):
    builds a :class:`DPTrainer` from ``model``/``example_input`` on every
    re-mesh. Kept as the config-5 workhorse; ZeRO-1/FSDP go through
    :class:`ElasticTrainer` with their own factory."""

    def __init__(
        self,
        model,
        devices_by_node: Mapping[int, Sequence[jax.Device]],
        example_input: np.ndarray,
        *,
        mesh_factory: Callable[..., jax.sharding.Mesh] = line_mesh,
        detector: PhiAccrualFailureDetector | None = None,
        min_nodes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        **trainer_kwargs,
    ) -> None:
        example = np.asarray(example_input)

        def factory(mesh, compress=_INHERIT):
            kw = dict(trainer_kwargs)
            if compress is not _INHERIT:
                kw["compress"] = compress
                if not compress:
                    # EF needs a lossy wire: a policy restore to full
                    # fidelity rebuilds without the residual (there is
                    # nothing withheld to carry)
                    kw.pop("error_feedback", None)
            return DPTrainer(
                model, mesh, example_input=example, **kw
            )

        super().__init__(
            factory,
            devices_by_node,
            mesh_factory=mesh_factory,
            detector=detector,
            min_nodes=min_nodes,
            clock=clock,
        )
        self.model = model
        self.example_input = example
