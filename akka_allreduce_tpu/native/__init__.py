"""ctypes binding for the C++ host-side reduction engine.

The TPU data plane is XLA (comm/allreduce.py); this native library carries the
*host* data path — engine unit mode, CPU fallback, DCN chunk staging — the
role the reference's JVM float loops play (SURVEY.md §3 "Reduction executor").
Built from ``native/threshold_reduce.cpp`` via ``make -C native`` or, failing
that, compiled on first import when a C++ toolchain is present. Every entry
point has a numpy fallback, so the framework is fully functional without the
.so; ``available()`` reports which path is live.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "_threshold_reduce.so")
_SRC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "threshold_reduce.cpp",
)

_lib = None
_lock = threading.Lock()
_build_attempted = False

_f32p = ctypes.POINTER(ctypes.c_float)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _try_build() -> bool:
    if not os.path.exists(_SRC_PATH):
        return False
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-fPIC", "-shared", "-fopenmp", "-std=c++17",
        _SRC_PATH, "-o", _SO_PATH,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.info("native build unavailable (%s); using numpy fallback", e)
        return False


def _load():
    global _lib, _build_attempted
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build_attempted:
            _build_attempted = True
            _try_build()
        if not os.path.exists(_SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            log.warning("could not load %s: %s", _SO_PATH, e)
            return None
        lib.ar_accumulate.argtypes = [_f32p, _f32p, ctypes.c_int64]
        lib.ar_masked_reduce.argtypes = [
            _f32p, _f32p, ctypes.c_int64, ctypes.c_int64, _f32p,
        ]
        lib.ar_masked_reduce.restype = ctypes.c_float
        lib.ar_average.argtypes = [_f32p, _i32p, _f32p, ctypes.c_int64]
        lib.ar_elastic_update.argtypes = [
            _f32p, _f32p, _i32p, ctypes.c_float, ctypes.c_int64,
        ]
        lib.ar_expand_counts.argtypes = [
            _i32p, _i64p, ctypes.c_int64, _i32p, ctypes.c_int64,
        ]
        lib.ar_abi_version.restype = ctypes.c_int
        if lib.ar_abi_version() != 1:
            log.warning("native ABI mismatch; using numpy fallback")
            return None
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def _fp(a: np.ndarray):
    return a.ctypes.data_as(_f32p)


def _ip(a: np.ndarray):
    return a.ctypes.data_as(_i32p)


def _writable_f32(a: np.ndarray, name: str) -> np.ndarray:
    if a.dtype != np.float32 or not a.flags.c_contiguous or not a.flags.writeable:
        raise ValueError(f"{name} must be writable C-contiguous float32")
    return a


def accumulate(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src, in place (float32)."""
    # numpy's in-place add is already optimal single-threaded; the native
    # kernel only wins when OpenMP has cores to spread across (the fused
    # kernels below win regardless, by skipping temporaries). Gate BEFORE
    # _load(): small-buffer deployments must never pay the lazy first build.
    if dst.size < 16384 or (os.cpu_count() or 1) < 2 or (lib := _load()) is None:
        dst += src.astype(np.float32, copy=False)
        return
    _writable_f32(dst, "dst")
    src = np.ascontiguousarray(src, dtype=np.float32)
    if src.shape != dst.shape:
        raise ValueError(f"shape mismatch: {dst.shape} vs {src.shape}")
    lib.ar_accumulate(_fp(dst), _fp(src), dst.size)


def masked_reduce(srcs: np.ndarray, valid: np.ndarray) -> tuple[np.ndarray, float]:
    """Fused ``(sum_j valid[j]*srcs[j], sum(valid))`` over ``srcs: (k, n)``."""
    srcs = np.ascontiguousarray(srcs, dtype=np.float32)
    valid = np.ascontiguousarray(valid, dtype=np.float32)
    if srcs.ndim != 2 or valid.shape != (srcs.shape[0],):
        raise ValueError(f"need srcs (k, n) and valid (k,); got {srcs.shape}, {valid.shape}")
    lib = _load()
    if lib is None:
        out = (srcs * valid[:, None]).sum(axis=0, dtype=np.float32)
        return out, float(valid.sum())
    out = np.empty(srcs.shape[1], dtype=np.float32)
    count = lib.ar_masked_reduce(
        _fp(srcs), _fp(valid), srcs.shape[0], srcs.shape[1], _fp(out)
    )
    return out, float(count)


def average(total: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """``total / counts`` where count > 0 else 0 (the consumer divide)."""
    total = np.ascontiguousarray(total, dtype=np.float32)
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    if counts.shape != total.shape:
        raise ValueError(f"shape mismatch: {total.shape} vs {counts.shape}")
    lib = _load()
    if lib is None:
        return np.where(
            counts > 0, total / np.maximum(counts, 1), np.float32(0.0)
        ).astype(np.float32)
    out = np.empty_like(total)
    lib.ar_average(_fp(total), _ip(counts), _fp(out), total.size)
    return out


def elastic_update(
    w: np.ndarray, total: np.ndarray, counts: np.ndarray, alpha: float
) -> None:
    """In place: ``w <- (1-a)*w + a*total/counts`` where count > 0."""
    _writable_f32(w, "w")
    total = np.ascontiguousarray(total, dtype=np.float32)
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    if total.shape != w.shape or counts.shape != w.shape:
        raise ValueError("w, total, counts must all share one shape")
    lib = _load()
    if lib is None:
        contributed = counts > 0
        avg = total / np.maximum(counts, 1)
        np.copyto(w, np.where(contributed, (1 - alpha) * w + alpha * avg, w))
        return
    lib.ar_elastic_update(_fp(w), _fp(total), _ip(counts), alpha, w.size)


def expand_counts(
    chunk_counts: np.ndarray, lengths: np.ndarray, n_out: int
) -> np.ndarray:
    """Per-chunk counts -> per-element counts (ReducedDataBuffer.get_with_counts)."""
    chunk_counts = np.ascontiguousarray(chunk_counts, dtype=np.int32).reshape(-1)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64).reshape(-1)
    if chunk_counts.shape != lengths.shape:
        raise ValueError("chunk_counts and lengths must align")
    lib = _load()
    if lib is None:
        out = np.zeros(n_out, dtype=np.int32)
        rep = np.repeat(chunk_counts, lengths)[:n_out]
        out[: rep.size] = rep  # zero-pad short inputs, same as the kernel
        return out
    out = np.zeros(n_out, dtype=np.int32)
    lib.ar_expand_counts(
        _ip(chunk_counts),
        lengths.ctypes.data_as(_i64p),
        chunk_counts.size,
        _ip(out),
        n_out,
    )
    return out
