"""ctypes binding for the C++ host-side reduction engine.

The TPU data plane is XLA (comm/allreduce.py); this native library carries the
*host* data path — engine unit mode, CPU fallback, DCN chunk staging — the
role the reference's JVM float loops play (SURVEY.md §3 "Reduction executor").
Built from ``threshold_reduce.cpp`` (shipped as package data, so installed
copies can build too) via ``make -C native`` or, failing that, compiled on
first import when a C++ toolchain is present. Every entry point has a numpy
fallback, so the framework is fully functional without the .so;
``available()`` reports which path is live.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "_threshold_reduce.so")
# threshold_reduce.cpp: reduction kernels; wire.cpp: payload-frame codec hot
# loop (header pack/unpack + checksum) — one .so, one loader, one ABI.
_SRC_PATHS = [
    os.path.join(os.path.dirname(__file__), "threshold_reduce.cpp"),
    os.path.join(os.path.dirname(__file__), "wire.cpp"),
]
_SRC_PATH = _SRC_PATHS[0]  # sentinel the build/test machinery stats

_ABI_VERSION = 5

_lib = None
_lock = threading.Lock()
_build_thread: threading.Thread | None = None
_load_failed = False

_f32p = ctypes.POINTER(ctypes.c_float)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)


# Canonical compile flags — native/Makefile shims to build() below, so this is
# the single source of truth.
_CXXFLAGS = ["-O3", "-fPIC", "-shared", "-fopenmp", "-Wall", "-std=c++17"]

# accumulate() routes buffers smaller than this to numpy (in-place add is
# already optimal single-threaded; OpenMP only wins with work to spread).
_ACCUM_NATIVE_MIN = 16384

# wire codec routes payloads smaller than this (bytes) to struct/numpy — a
# ctypes call costs ~1us of marshalling, so tiny frames are faster in Python.
_WIRE_NATIVE_MIN = 16384


def _try_build() -> bool:
    if not os.path.exists(_SRC_PATH):
        return False
    # Compile to a per-process-per-thread temp path, then rename into place:
    # N worker processes (or a background build racing an explicit build())
    # may compile concurrently, and os.replace is atomic on POSIX — nobody
    # ever CDLLs a half-written file.
    tmp = f"{_SO_PATH}.tmp.{os.getpid()}.{threading.get_ident()}"
    srcs = [p for p in _SRC_PATHS if os.path.exists(p)]
    cmd = [os.environ.get("CXX", "g++"), *_CXXFLAGS, *srcs, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        log.info("native build unavailable (%s); using numpy fallback", e)
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _ensure_build(wait: bool) -> None:
    """Kick off (or join) the one-time background build of the .so."""
    global _build_thread
    with _lock:
        if os.path.exists(_SO_PATH):
            return
        if _build_thread is None:
            _build_thread = threading.Thread(
                target=_try_build, name="native-build", daemon=True
            )
            _build_thread.start()
        thread = _build_thread
    if wait:
        thread.join(timeout=150)


def _bind(lib) -> None:
    """Declare argtypes; raises AttributeError on a stale .so missing symbols."""
    lib.ar_abi_version.restype = ctypes.c_int
    lib.ar_accumulate.argtypes = [_f32p, _f32p, ctypes.c_int64]
    lib.ar_average.argtypes = [_f32p, _i32p, _f32p, ctypes.c_int64]
    lib.ar_elastic_update.argtypes = [
        _f32p, _f32p, _i32p, ctypes.c_float, ctypes.c_int64,
    ]
    lib.ar_expand_counts.argtypes = [
        _i32p, _i64p, ctypes.c_int64, _i32p, ctypes.c_int64,
    ]
    lib.aw_checksum.argtypes = [_u8p, ctypes.c_int64]
    lib.aw_checksum.restype = ctypes.c_uint32
    lib.aw_pack_block.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int32, _u8p, ctypes.c_int64, ctypes.c_uint32,
    ]
    lib.aw_pack_block.restype = ctypes.c_int
    lib.aw_unpack_block.argtypes = [_u8p, ctypes.c_int64, _i64p]
    lib.aw_unpack_block.restype = ctypes.c_int64
    lib.aw_have_sendmmsg.argtypes = []
    lib.aw_have_sendmmsg.restype = ctypes.c_int
    _u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.aw_sendmmsg.argtypes = [
        ctypes.c_int, _u64p, _i64p, _i32p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.aw_sendmmsg.restype = ctypes.c_int64
    lib.aw_recvmmsg.argtypes = [
        ctypes.c_int, _u64p, _i64p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.aw_recvmmsg.restype = ctypes.c_int64
    lib.aw_have_uring.argtypes = []
    lib.aw_have_uring.restype = ctypes.c_int
    lib.aw_uring_probe_errno.argtypes = []
    lib.aw_uring_probe_errno.restype = ctypes.c_int
    lib.aw_uring_create.argtypes = [ctypes.c_int]
    lib.aw_uring_create.restype = ctypes.c_void_p
    lib.aw_uring_close.argtypes = [ctypes.c_void_p]
    lib.aw_uring_close.restype = None
    lib.aw_uring_sendmsg.argtypes = [
        ctypes.c_void_p, ctypes.c_int, _u64p, _i64p, ctypes.c_int32,
    ]
    lib.aw_uring_sendmsg.restype = ctypes.c_int64


def _load(*, build_wait: bool = False, _retried: bool = False):
    """Return the bound library or None (numpy fallback).

    Hot-path callers use the default ``build_wait=False``: a missing .so
    starts ONE background compile and the caller falls back to numpy until it
    lands — a round-completion path must never stall ~2min on a g++ run.
    ``available()`` passes ``build_wait=True`` (explicit capability query).

    A stale artifact (old ABI / missing symbols / corrupt ELF) is removed and
    rebuilt from the current source once; only a failure with no way forward
    (no toolchain, removal refused) latches ``_load_failed`` so hot paths
    short-circuit without re-stat/re-dlopen per message.
    """
    global _lib, _load_failed, _build_thread
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    if not os.path.exists(_SO_PATH):
        _ensure_build(wait=build_wait)
        if not os.path.exists(_SO_PATH):
            with _lock:
                # build thread finished and still no artifact: terminal
                if (
                    _build_thread is not None
                    and not _build_thread.is_alive()
                    and not os.path.exists(_SO_PATH)
                ):
                    _load_failed = True
            return None
    retry = False
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        lib = None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            _bind(lib)
            if lib.ar_abi_version() != _ABI_VERSION:
                raise AttributeError(
                    f"ABI {lib.ar_abi_version()} != {_ABI_VERSION}"
                )
        except (OSError, AttributeError) as e:
            log.warning("stale/unloadable %s (%s)", _SO_PATH, e)
            if lib is not None:
                # dlclose the failed handle: glibc dlopen dedupes by path, so
                # a still-open stale image would shadow the rebuilt file
                try:
                    import _ctypes

                    _ctypes.dlclose(lib._handle)
                except Exception:  # pragma: no cover - best effort
                    pass
            removed = True
            try:
                os.remove(_SO_PATH)
            except FileNotFoundError:
                pass  # a concurrent loader already removed it — proceed
            except OSError:
                removed = False
            if removed:
                _build_thread = None  # allow a fresh build of current source
                retry = not _retried
            if not retry:
                _load_failed = True
                return None
        else:
            _lib = lib
            return lib
    # stale artifact removed: one rebuild + reload attempt (async unless the
    # caller asked to wait)
    return _load(build_wait=build_wait, _retried=True)


def available() -> bool:
    return _load(build_wait=True) is not None


def loaded() -> bool:
    """True iff the native library is loaded RIGHT NOW — never builds,
    never blocks. This is the provenance query: ``available()`` may spend
    ~2 min compiling and then truthfully answer "yes" about a library the
    measurement it labels never used."""
    return _lib is not None


def build() -> bool:
    """Force a synchronous rebuild from source (``make -C native`` shims here).

    Returns True iff the library built and loaded.
    """
    global _lib, _load_failed, _build_thread
    with _lock:
        _lib = None
        _load_failed = False
        _build_thread = None
        if os.path.exists(_SO_PATH):
            try:
                os.remove(_SO_PATH)
            except OSError:
                return False
    return _try_build() and _load() is not None


def _fp(a: np.ndarray):
    return a.ctypes.data_as(_f32p)


def _ip(a: np.ndarray):
    return a.ctypes.data_as(_i32p)


def _writable_f32(a: np.ndarray, name: str) -> np.ndarray:
    if a.dtype != np.float32 or not a.flags.c_contiguous or not a.flags.writeable:
        raise ValueError(f"{name} must be writable C-contiguous float32")
    return a


def accumulate(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src, in place (float32)."""
    # numpy's in-place add is already optimal single-threaded; the native
    # kernel only wins when OpenMP has cores to spread across (the fused
    # kernels below win regardless, by skipping temporaries). Gate BEFORE
    # _load(): small-buffer deployments must never pay the lazy first build.
    if (
        dst.size < _ACCUM_NATIVE_MIN
        or (os.cpu_count() or 1) < 2
        or (lib := _load()) is None
    ):
        dst += src.astype(np.float32, copy=False)
        return
    _writable_f32(dst, "dst")
    src = np.ascontiguousarray(src, dtype=np.float32)
    if src.shape != dst.shape:
        raise ValueError(f"shape mismatch: {dst.shape} vs {src.shape}")
    lib.ar_accumulate(_fp(dst), _fp(src), dst.size)


def average(total: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """``total / counts`` where count > 0 else 0 (the consumer divide)."""
    total = np.ascontiguousarray(total, dtype=np.float32)
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    if counts.shape != total.shape:
        raise ValueError(f"shape mismatch: {total.shape} vs {counts.shape}")
    lib = _load()
    if lib is None:
        return np.where(
            counts > 0, total / np.maximum(counts, 1), np.float32(0.0)
        ).astype(np.float32)
    out = np.empty_like(total)
    lib.ar_average(_fp(total), _ip(counts), _fp(out), total.size)
    return out


def elastic_update(
    w: np.ndarray, total: np.ndarray, counts: np.ndarray, alpha: float
) -> None:
    """In place: ``w <- (1-a)*w + a*total/counts`` where count > 0."""
    _writable_f32(w, "w")
    total = np.ascontiguousarray(total, dtype=np.float32)
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    if total.shape != w.shape or counts.shape != w.shape:
        raise ValueError("w, total, counts must all share one shape")
    lib = _load()
    if lib is None:
        contributed = counts > 0
        avg = total / np.maximum(counts, 1)
        np.copyto(w, np.where(contributed, (1 - alpha) * w + alpha * avg, w))
        return
    lib.ar_elastic_update(_fp(w), _fp(total), _ip(counts), alpha, w.size)


def expand_counts(
    chunk_counts: np.ndarray, lengths: np.ndarray, n_out: int
) -> np.ndarray:
    """Per-chunk counts -> per-element counts (ReducedDataBuffer.get_with_counts)."""
    chunk_counts = np.ascontiguousarray(chunk_counts, dtype=np.int32).reshape(-1)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64).reshape(-1)
    if chunk_counts.shape != lengths.shape:
        raise ValueError("chunk_counts and lengths must align")
    lib = _load()
    if lib is None:
        out = np.zeros(n_out, dtype=np.int32)
        rep = np.repeat(chunk_counts, lengths)[:n_out]
        out[: rep.size] = rep  # zero-pad short inputs, same as the kernel
        return out
    out = np.zeros(n_out, dtype=np.int32)
    lib.ar_expand_counts(
        _ip(chunk_counts),
        lengths.ctypes.data_as(_i64p),
        chunk_counts.size,
        _ip(out),
        n_out,
    )
    return out


# -- wire codec hot loop (control/wire.py payload frames) ----------------------
#
# Frame body layout (tag 2 = ScatterBlock <iiiq>, tag 3 = ReduceBlock <iiiqi>):
#   [tag u8][fields][count_word u32][checksum u32][payload bytes]
# The count word's top bit flags float16 payloads (wire._F16_FLAG); the
# checksum is the additive sum of the payload's LE u32 words mod 2^32 (tail
# zero-padded). These wrappers
# collapse the per-frame work to ONE native call each way when the payload is
# large enough to amortize the ctypes marshalling, with an exact struct/numpy
# fallback otherwise — same bytes either path.

_F16_FLAG = 0x8000_0000  # keep in sync with control/wire.py and wire.cpp
_PACK_SCATTER = struct.Struct("<BiiiqII")
_PACK_REDUCE = struct.Struct("<BiiiqiII")


def _u8(mv: memoryview) -> np.ndarray:
    return np.frombuffer(mv, dtype=np.uint8)


def _byte_view(buf) -> memoryview:
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    return mv if mv.format == "B" and mv.contiguous else mv.cast("B")


def wire_checksum(buf) -> int:
    """Additive sum of little-endian u32 words mod 2^32 (tail zero-padded)
    of ``buf`` — native when it pays off, numpy otherwise, same value."""
    mv = _byte_view(buf)
    n = mv.nbytes
    if n == 0:
        return 0
    if n >= _WIRE_NATIVE_MIN and (lib := _load()) is not None:
        return int(lib.aw_checksum(_u8(mv).ctypes.data_as(_u8p), n))
    n4 = n & ~3
    s = (
        int(np.add.reduce(np.frombuffer(mv[:n4], "<u4"), dtype=np.uint32))
        if n4
        else 0
    )
    if n4 < n:
        s = (s + int.from_bytes(bytes(mv[n4:n]), "little")) & 0xFFFF_FFFF
    return s


def pack_block_header(
    tag: int,
    src_id: int,
    dest_id: int,
    chunk_id: int,
    round_num: int,
    count: int,
    payload,
    count_word: int,
) -> bytes:
    """``[tag][fields][count_word][checksum]`` for a payload frame — the
    checksum pass over ``payload`` and the header pack are one native call."""
    mv = _byte_view(payload)
    n = mv.nbytes
    if n >= _WIRE_NATIVE_MIN and (lib := _load()) is not None:
        out = (ctypes.c_uint8 * 40)()
        ln = lib.aw_pack_block(
            out, tag, src_id, dest_id, chunk_id, round_num, count,
            _u8(mv).ctypes.data_as(_u8p), n, count_word,
        )
        if ln > 0:
            return bytes(out[:ln])
    ck = wire_checksum(mv)
    if tag == 2:
        return _PACK_SCATTER.pack(
            2, src_id, dest_id, chunk_id, round_num, count_word, ck
        )
    if tag == 3:
        return _PACK_REDUCE.pack(
            3, src_id, dest_id, chunk_id, round_num, count, count_word, ck
        )
    raise ValueError(f"not a payload frame tag: {tag}")


def unpack_block(body) -> tuple[int, int, int, int, int, int, bool, int]:
    """Parse + checksum-verify a payload frame body (starting at the tag).

    Returns ``(src_id, dest_id, chunk_id, round_num, count, n_elems, is_f16,
    payload_offset)``; raises ``ValueError`` on truncation / checksum
    mismatch / non-payload tag. The caller slices the payload out of ``body``
    at the returned offset — no copy happens here.
    """
    mv = _byte_view(body)
    n = mv.nbytes
    if n >= _WIRE_NATIVE_MIN and (lib := _load()) is not None:
        out = (ctypes.c_int64 * 7)()
        off = int(
            lib.aw_unpack_block(_u8(mv).ctypes.data_as(_u8p), n, out)
        )
        if off == -2:
            raise ValueError("payload checksum mismatch")
        if off < 0:
            raise ValueError(f"malformed payload frame (code {off})")
        return (
            int(out[0]), int(out[1]), int(out[2]), int(out[3]), int(out[4]),
            int(out[5]), bool(out[6]), off,
        )
    if n < 1:
        raise ValueError("empty payload frame")
    tag = mv[0]
    try:
        if tag == 2:
            src, dest, chunk, rnd = struct.unpack_from("<iiiq", mv, 1)
            count, off = 0, 21
        elif tag == 3:
            src, dest, chunk, rnd, count = struct.unpack_from("<iiiqi", mv, 1)
            off = 25
        else:
            raise ValueError(f"not a payload frame tag: {tag}")
        count_word, ck = struct.unpack_from("<II", mv, off)
    except struct.error as exc:  # same contract as the native path: ValueError
        raise ValueError(f"truncated payload frame header ({exc})") from exc
    off += 8
    n_elems = count_word & ~_F16_FLAG
    is_f16 = bool(count_word & _F16_FLAG)
    nbytes = n_elems * (2 if is_f16 else 4)
    if off + nbytes > n:
        raise ValueError("truncated payload")
    if wire_checksum(mv[off : off + nbytes]) != ck:
        raise ValueError("payload checksum mismatch")
    return (src, dest, chunk, rnd, count, n_elems, is_f16, off)


# -- batch syscalls (wire.cpp aw_sendmmsg/aw_recvmmsg) -------------------------
#
# The multi-stream senders drain a burst of frames in one syscall per stream.
# Wire bytes are IDENTICAL either path (batching is pure syscall coalescing);
# the plain sendmsg loop is compiled in unconditionally and selected at
# runtime — by the kernel's ENOSYS answer, or by force_fallback for the
# byte-identity pin in tests.


def batch_send_available() -> bool:
    """True iff the native batch-send entry point is loadable (the Python
    caller keeps its own socket.sendmsg loop for when it is not)."""
    return _load() is not None


def sendmmsg_available() -> bool:
    """True iff the RUNNING kernel implements sendmmsg (runtime probe);
    False also when the native library itself is unavailable."""
    lib = _load()
    return bool(lib is not None and lib.aw_have_sendmmsg())


def _iovec_arrays(views: list) -> tuple[np.ndarray, np.ndarray, list]:
    """(bases u64, lens i64, keepalive) for a flat list of buffer views.

    The keepalive list pins the np.frombuffer wrappers (and thus the
    addresses) for the duration of the syscall."""
    keep = []
    bases = np.empty(len(views), dtype=np.uint64)
    lens = np.empty(len(views), dtype=np.int64)
    for i, v in enumerate(views):
        arr = np.frombuffer(v, dtype=np.uint8)
        keep.append(arr)
        bases[i] = arr.ctypes.data
        lens[i] = arr.nbytes
    return bases, lens, keep


def batch_send(fd: int, frames: list[list], *, force_fallback: bool = False) -> int:
    """Send ``frames`` (each a list of buffer segments) on connected stream
    socket ``fd`` in one ``sendmmsg`` (or the runtime-selected ``sendmsg``
    loop). Returns bytes sent — short counts and partial trailing frames
    are normal; the caller advances its views and re-enters. Raises
    ``BlockingIOError`` when nothing could be sent (EAGAIN) and ``OSError``
    for other errnos; ``RuntimeError`` when the native library is absent
    (query :func:`batch_send_available` first)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native wire library unavailable")
    flat: list = []
    counts = np.empty(len(frames), dtype=np.int32)
    for i, parts in enumerate(frames):
        parts = [p for p in parts if len(p)]
        counts[i] = len(parts)
        flat.extend(parts)
    bases, lens, _keep = _iovec_arrays(flat)
    n = int(
        lib.aw_sendmmsg(
            fd,
            bases.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(_i64p),
            counts.ctypes.data_as(_i32p),
            len(frames),
            1 if force_fallback else 0,
        )
    )
    if n < 0:
        import errno as _errno

        if -n in (_errno.EAGAIN, _errno.EWOULDBLOCK):
            raise BlockingIOError(-n, os.strerror(-n))
        raise OSError(-n, os.strerror(-n))
    return n


# -- io_uring submission (wire.cpp aw_uring_*) ---------------------------------
#
# The next syscall step past sendmmsg: one ring submission drains a whole
# burst (a single SENDMSG op gathering every frame segment — the byte stream
# cannot interleave). Runtime-probed like the batch syscalls; the probe's
# REASON is exported so bench-wire records why a box fell back instead of
# silently benching the wrong lever.


def uring_available() -> bool:
    """True iff the native library is loaded AND the running kernel's
    io_uring probe passed (setup accepted + SENDMSG supported)."""
    lib = _load()
    return bool(lib is not None and lib.aw_have_uring())


def uring_probe_reason() -> str:
    """Why io_uring is (un)usable here: ``"ok"`` when the probe passed,
    else a stable reason code — ``"no-native"`` (library not built),
    ``"enosys"`` (pre-5.1 kernel), ``"eperm"`` (seccomp/gVisor policy),
    ``"op-unsupported"`` (ring exists, SENDMSG does not), or
    ``"errno:<n>"`` for anything else the setup syscall answered."""
    import errno as _errno

    lib = _load()
    if lib is None:
        return "no-native"
    code = int(lib.aw_uring_probe_errno())
    if code == 0:
        return "ok"
    if code == _errno.ENOSYS:
        return "enosys"
    if code == _errno.EPERM:
        return "eperm"
    if code == _errno.EOPNOTSUPP:
        return "op-unsupported"
    return f"errno:{code}"


class UringRing:
    """One sender thread's submission ring (never shared across threads).

    ``send`` takes a FLAT list of buffer segments and moves them through
    one ring submission; short counts are normal (the caller advances its
    views and re-enters, exactly the ``batch_send`` contract). Raises
    ``RuntimeError`` at construction when io_uring is unusable here —
    callers probe :func:`uring_available` first and keep the
    sendmmsg/sendmsg path as the fallback."""

    __slots__ = ("_handle", "_lib")

    def __init__(self, entries: int = 8) -> None:
        lib = _load()
        if lib is None or not lib.aw_have_uring():
            raise RuntimeError(
                f"io_uring unavailable ({uring_probe_reason()})"
            )
        handle = lib.aw_uring_create(entries)
        if not handle:
            raise RuntimeError("io_uring ring creation failed")
        self._lib = lib
        self._handle = handle

    def send(self, fd: int, views: list) -> int:
        """Send ``views`` (flat buffer segments) on connected stream
        socket ``fd``; returns bytes moved, raises ``BlockingIOError`` /
        ``OSError`` like :func:`batch_send`."""
        import errno as _errno

        bases, lens, _keep = _iovec_arrays(views)
        n = int(
            self._lib.aw_uring_sendmsg(
                self._handle,
                fd,
                bases.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                lens.ctypes.data_as(_i64p),
                len(views),
            )
        )
        if n < 0:
            if -n in (_errno.EAGAIN, _errno.EWOULDBLOCK):
                raise BlockingIOError(-n, os.strerror(-n))
            raise OSError(-n, os.strerror(-n))
        return n

    def close(self) -> None:
        handle, self._handle = self._handle, None
        if handle:
            self._lib.aw_uring_close(handle)

    def __del__(self) -> None:  # best-effort: rings also close with the fd
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown
            pass


def batch_recv(fd: int, bufs: list, *, force_fallback: bool = False) -> int:
    """Receive into ``bufs`` (writable buffers, filled in order) from
    stream socket ``fd`` via ``recvmmsg`` (or the recvmsg loop). Returns
    total bytes read (0 = orderly EOF); raises like :func:`batch_send`."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native wire library unavailable")
    bases, lens, _keep = _iovec_arrays(bufs)
    n = int(
        lib.aw_recvmmsg(
            fd,
            bases.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(_i64p),
            len(bufs),
            1 if force_fallback else 0,
        )
    )
    if n < 0:
        import errno as _errno

        if -n in (_errno.EAGAIN, _errno.EWOULDBLOCK):
            raise BlockingIOError(-n, os.strerror(-n))
        raise OSError(-n, os.strerror(-n))
    return n
