// Host data-plane wire hot loop: payload-frame header pack/unpack + checksum.
//
// The Python codec (control/wire.py) frames float payloads as
//   [tag u8][fields][count_word u32][checksum u32][payload bytes]
// where tag 2 = ScatterBlock (fields <iiiq>), tag 3 = ReduceBlock (<iiiqi>),
// the count word's top bit flags a float16 payload, and the checksum is the
// additive sum of the payload's LE u32 words mod 2^32, tail zero-padded
// (matches native.wire_checksum's numpy fallback exactly).
//
// These two entry points collapse the per-frame Python work — struct packs,
// bounds checks, and the full-payload checksum pass — into ONE ctypes call
// each way, so the per-byte cost of a payload frame is a single vectorized
// read (the checksum) with no intermediate allocation. Byte order is written
// explicitly little-endian so the wire format is host-independent.
//
// Compiled into the same .so as threshold_reduce.cpp (one loader, one ABI).

#include <cstdint>
#include <cstring>

#if defined(__linux__) || defined(__APPLE__)
#define AW_HAVE_SOCKETS 1
#include <cerrno>
#include <sys/socket.h>
#include <sys/uio.h>
#endif

namespace {

inline void put_le32(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)(v);
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

inline void put_le64(uint8_t* p, uint64_t v) {
  put_le32(p, (uint32_t)v);
  put_le32(p + 4, (uint32_t)(v >> 32));
}

inline uint32_t get_le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline uint64_t get_le64(const uint8_t* p) {
  return (uint64_t)get_le32(p) | ((uint64_t)get_le32(p + 4) << 32);
}

constexpr uint32_t kF16Flag = 0x80000000u;  // wire.py _F16_FLAG

}  // namespace

extern "C" {

// Additive payload checksum: sum of little-endian u32 words mod 2^32, the
// tail (payloads are always a multiple of 2 bytes) zero-padded. A word sum
// vectorizes to memory speed — one read pass, ~8x cheaper than the memcpy
// it replaces on the old join/readexactly path — and catches the framing
// corruptions the transport actually sees (truncation, garbage bodies).
uint32_t aw_checksum(const uint8_t* data, int64_t n) {
  int64_t n4 = n >> 2;
  uint64_t s = 0;
#pragma omp parallel for schedule(static) reduction(+ : s) if (n4 > 262144)
  for (int64_t i = 0; i < n4; ++i) {
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
    // memcpy load: one unaligned mov per word — decode checksums run at
    // payload offsets the dest-string length makes misaligned, where the
    // byte-shift form costs ~5x
    uint32_t w;
    __builtin_memcpy(&w, data + 4 * i, 4);
    s += w;
#else
    s += get_le32(data + 4 * i);
#endif
  }
  uint32_t tail = 0;
  for (int64_t i = n4 * 4, shift = 0; i < n; ++i, shift += 8)
    tail |= (uint32_t)data[i] << shift;
  return (uint32_t)(s + tail);
}

// Pack [tag][fields][count_word][checksum] for a payload frame and compute
// the checksum of `payload` in the same call. Returns the header length
// written into `out` (caller provides >= 34 bytes), or -1 on unknown tag.
int aw_pack_block(uint8_t* out, int tag, int32_t src_id, int32_t dest_id,
                  int32_t chunk_id, int64_t round_num, int32_t count,
                  const uint8_t* payload, int64_t payload_bytes,
                  uint32_t count_word) {
  if (tag != 2 && tag != 3) return -1;
  uint8_t* p = out;
  *p++ = (uint8_t)tag;
  put_le32(p, (uint32_t)src_id);
  put_le32(p + 4, (uint32_t)dest_id);
  put_le32(p + 8, (uint32_t)chunk_id);
  put_le64(p + 12, (uint64_t)round_num);
  p += 20;
  if (tag == 3) {
    put_le32(p, (uint32_t)count);
    p += 4;
  }
  put_le32(p, count_word);
  put_le32(p + 4, aw_checksum(payload, payload_bytes));
  p += 8;
  return (int)(p - out);
}

// Parse + verify a payload frame body starting at the tag byte. Fills
// out[0..6] = {src_id, dest_id, chunk_id, round_num, count, n_elems, is_f16}
// and returns the payload byte offset, or -1 (truncated) / -2 (checksum
// mismatch) / -3 (not a payload tag).
int64_t aw_unpack_block(const uint8_t* body, int64_t nbytes, int64_t* out) {
  if (nbytes < 1) return -1;
  int tag = body[0];
  if (tag != 2 && tag != 3) return -3;
  int64_t off = 1 + 20 + (tag == 3 ? 4 : 0) + 8;  // fields + count word + checksum
  if (nbytes < off) return -1;
  const uint8_t* p = body + 1;
  out[0] = (int32_t)get_le32(p);
  out[1] = (int32_t)get_le32(p + 4);
  out[2] = (int32_t)get_le32(p + 8);
  out[3] = (int64_t)get_le64(p + 12);
  out[4] = tag == 3 ? (int32_t)get_le32(p + 20) : 0;
  uint32_t count_word = get_le32(body + off - 8);
  uint32_t checksum = get_le32(body + off - 4);
  int is_f16 = (count_word & kF16Flag) != 0;
  int64_t n_elems = (int64_t)(count_word & ~kF16Flag);
  out[5] = n_elems;
  out[6] = is_f16;
  int64_t payload_bytes = n_elems * (is_f16 ? 2 : 4);
  if (payload_bytes > nbytes - off) return -1;
  if (aw_checksum(body + off, payload_bytes) != checksum) return -2;
  return off;
}

}  // extern "C"

// -- batch syscalls (multi-stream data plane, BENCHMARKS.md round 8) ---------
//
// One coalesced burst of frames drains in ONE syscall per stream:
// `aw_sendmmsg` maps a (bases, lens, counts) flattening of per-frame iovec
// lists onto Linux's sendmmsg(2) — message m owns counts[m] consecutive
// iovecs — and `aw_recvmmsg` is its receive-side mirror over recvmmsg(2).
// Neither changes a single wire byte: batching is pure syscall coalescing,
// and the plain sendmsg/recvmsg LOOP fallback below is compiled in
// unconditionally and selected at RUNTIME (first-call ENOSYS probe, or
// use_fallback=1 for tests pinning byte-identical output). Both return
// total bytes moved (callers advance their views and re-enter — short
// counts and partial trailing messages are normal on stream sockets), or
// -errno when nothing moved.

#if AW_HAVE_SOCKETS
namespace {

constexpr int kMaxBatchMsgs = 64;
constexpr int kMaxBatchIovs = 1024;

int64_t sendmsg_loop(int fd, const uint64_t* bases, const int64_t* lens,
                     const int32_t* counts, int32_t nmsgs) {
  int64_t total = 0;
  int64_t iov_off = 0;
  for (int32_t m = 0; m < nmsgs; ++m) {
    struct iovec iov[kMaxBatchIovs];
    int32_t cnt = counts[m];
    if (cnt > kMaxBatchIovs) return total > 0 ? total : -EINVAL;
    int64_t want = 0;
    for (int32_t i = 0; i < cnt; ++i) {
      iov[i].iov_base = (void*)(uintptr_t)bases[iov_off + i];
      iov[i].iov_len = (size_t)lens[iov_off + i];
      want += lens[iov_off + i];
    }
    struct msghdr hdr;
    memset(&hdr, 0, sizeof(hdr));
    hdr.msg_iov = iov;
    hdr.msg_iovlen = cnt;
    ssize_t n = sendmsg(fd, &hdr, 0);
    if (n < 0) return total > 0 ? total : -(int64_t)errno;
    total += n;
    if (n < want) break;  // kernel buffer full mid-frame: caller re-enters
    iov_off += cnt;
  }
  return total;
}

}  // namespace
#endif  // AW_HAVE_SOCKETS

extern "C" {

// 1 iff the running kernel implements sendmmsg/recvmmsg (runtime probe, not
// a compile-time guess — the batch path must degrade on kernels/libcs that
// compiled fine but answer ENOSYS).
int aw_have_sendmmsg(void) {
#if defined(__linux__)
  static int cached = -1;
  if (cached < 0) {
    struct mmsghdr hdr;
    memset(&hdr, 0, sizeof(hdr));
    // fd -1 never touches a real socket: an implemented syscall answers
    // EBADF, an unimplemented one ENOSYS
    int r = sendmmsg(-1, &hdr, 1, 0);
    cached = (r >= 0 || errno != ENOSYS) ? 1 : 0;
  }
  return cached;
#else
  return 0;
#endif
}

// Batch send: nmsgs messages, message m owning counts[m] iovecs taken in
// order from (bases, lens). Returns total bytes written, or -errno when
// nothing was written. use_fallback != 0 forces the sendmsg loop.
int64_t aw_sendmmsg(int fd, const uint64_t* bases, const int64_t* lens,
                    const int32_t* counts, int32_t nmsgs,
                    int32_t use_fallback) {
#if !AW_HAVE_SOCKETS
  (void)fd; (void)bases; (void)lens; (void)counts; (void)nmsgs;
  (void)use_fallback;
  return -38;  // ENOSYS
#else
  if (nmsgs <= 0) return 0;
#if defined(__linux__)
  if (!use_fallback && aw_have_sendmmsg()) {
    struct mmsghdr hdrs[kMaxBatchMsgs];
    struct iovec iov[kMaxBatchIovs];
    int32_t n = nmsgs < kMaxBatchMsgs ? nmsgs : kMaxBatchMsgs;
    int64_t iov_off = 0;
    int32_t built = 0;
    for (; built < n; ++built) {
      int32_t cnt = counts[built];
      if (iov_off + cnt > kMaxBatchIovs) break;
      memset(&hdrs[built], 0, sizeof(hdrs[built]));
      for (int32_t i = 0; i < cnt; ++i) {
        iov[iov_off + i].iov_base = (void*)(uintptr_t)bases[iov_off + i];
        iov[iov_off + i].iov_len = (size_t)lens[iov_off + i];
      }
      hdrs[built].msg_hdr.msg_iov = &iov[iov_off];
      hdrs[built].msg_hdr.msg_iovlen = cnt;
      iov_off += cnt;
    }
    if (built > 0) {
      int r = sendmmsg(fd, hdrs, built, 0);
      if (r < 0) return -(int64_t)errno;
      int64_t total = 0;
      for (int i = 0; i < r; ++i) total += (int64_t)hdrs[i].msg_len;
      return total;
    }
    // first message alone overflows the iovec budget: fall through
  }
#endif  // __linux__
  return sendmsg_loop(fd, bases, lens, counts, nmsgs);
#endif  // AW_HAVE_SOCKETS
}

}  // extern "C"

// -- io_uring batch submission (data plane v3, BENCHMARKS.md round 9) --------
//
// The next syscall step past `sendmmsg`: a sender thread drains its whole
// burst through ONE ring submission — a single IORING_OP_SENDMSG whose iovec
// array gathers every frame segment of the batch (one msghdr, so the TCP
// byte stream can never interleave; linked-SQE chains are deliberately NOT
// used — a short send mid-chain would let a later message's bytes land
// after a partial earlier one). Wire bytes are identical to the
// sendmmsg/sendmsg paths; like them, this is pure submission mechanics.
//
// Everything io_uring is defined locally (struct layouts are kernel ABI,
// stable by contract) so this compiles against pre-5.1 kernel headers; the
// RUNTIME probe decides whether it runs: io_uring_setup answering ENOSYS
// (old kernel), EPERM (seccomp/gVisor), or a registration probe without
// SENDMSG support all fall through to the sendmmsg/sendmsg path, and the
// probe's errno is exported so bench-wire can RECORD the fallback reason.

#if defined(__linux__)
#include <new>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
#ifndef __NR_io_uring_register
#define __NR_io_uring_register 427
#endif

namespace {

// kernel ABI mirrors (include/uapi/linux/io_uring.h) — local names so a
// host that DOES ship the header cannot clash
struct aw_sqring_offsets {
  uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array, resv1;
  uint64_t resv2;
};
struct aw_cqring_offsets {
  uint32_t head, tail, ring_mask, ring_entries, overflow, cqes, flags, resv1;
  uint64_t resv2;
};
struct aw_uring_params {
  uint32_t sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle;
  uint32_t features, wq_fd, resv[3];
  struct aw_sqring_offsets sq_off;
  struct aw_cqring_offsets cq_off;
};
struct aw_uring_sqe {  // 64 bytes, exact kernel layout
  uint8_t opcode;
  uint8_t flags;
  uint16_t ioprio;
  int32_t fd;
  uint64_t off;
  uint64_t addr;
  uint32_t len;
  uint32_t msg_flags;  // union: rw_flags/fsync_flags/... — SENDMSG uses this
  uint64_t user_data;
  uint64_t pad2[3];
};
struct aw_uring_cqe {
  uint64_t user_data;
  int32_t res;
  uint32_t flags;
};
struct aw_uring_probe_op {
  uint8_t op, resv;
  uint16_t flags;  // bit 0 = IO_URING_OP_SUPPORTED
  uint32_t resv2;
};
struct aw_uring_probe {
  uint8_t last_op, ops_len;
  uint16_t resv;
  uint32_t resv2[3];
  struct aw_uring_probe_op ops[64];
};

constexpr uint8_t kOpSendmsg = 9;       // IORING_OP_SENDMSG
constexpr unsigned kEnterGetevents = 1; // IORING_ENTER_GETEVENTS
constexpr unsigned kRegisterProbe = 8;  // IORING_REGISTER_PROBE
constexpr uint32_t kFeatSingleMmap = 1; // IORING_FEAT_SINGLE_MMAP
constexpr off_t kOffSqRing = 0;
constexpr off_t kOffCqRing = 0x8000000;
constexpr off_t kOffSqes = 0x10000000;

struct AwUring {
  int ring_fd;
  int broken;  // an op was left in flight on an error path: never reuse
  unsigned sq_entries, cq_entries;
  unsigned *sq_head, *sq_tail, *sq_mask, *sq_array;
  unsigned *cq_head, *cq_tail, *cq_mask;
  struct aw_uring_sqe* sqes;
  struct aw_uring_cqe* cq_cqes;
  void *sq_ptr, *cq_ptr;
  size_t sq_len, cq_len, sqes_len;
  int single_mmap;
};

int aw_uring_probe_errno_ = -1;  // -1 = not probed; 0 = supported

int uring_setup(unsigned entries, struct aw_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
int uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                      flags, nullptr, 0);
}

}  // namespace
#endif  // __linux__

extern "C" {

// 1 iff the running kernel accepts io_uring_setup AND (when the kernel can
// answer) reports IORING_OP_SENDMSG supported. The verdict and the errno
// behind a negative one are cached; aw_uring_probe_errno() exports the
// reason (0 = supported, ENOSYS = pre-5.1 kernel, EPERM = seccomp/gVisor
// policy, EOPNOTSUPP = ring works but SENDMSG is not implemented).
int aw_have_uring(void) {
#if defined(__linux__)
  if (aw_uring_probe_errno_ >= 0) return aw_uring_probe_errno_ == 0;
  struct aw_uring_params params;
  memset(&params, 0, sizeof(params));
  int fd = uring_setup(4, &params);
  if (fd < 0) {
    aw_uring_probe_errno_ = errno ? errno : ENOSYS;
    return 0;
  }
  // SENDMSG needs kernel >= 5.3; the registration probe (>= 5.6) answers
  // authoritatively. A kernel too old for the probe op (EINVAL) but new
  // enough for io_uring is assumed capable — a 5.1/5.2 kernel would fail
  // the first real submit with EINVAL, which the caller latches into the
  // same fallback path at runtime.
  struct aw_uring_probe probe;
  memset(&probe, 0, sizeof(probe));
  long r = syscall(__NR_io_uring_register, fd, kRegisterProbe, &probe, 64);
  if (r == 0 &&
      (probe.last_op < kOpSendmsg || !(probe.ops[kOpSendmsg].flags & 1))) {
    aw_uring_probe_errno_ = EOPNOTSUPP;
  } else {
    aw_uring_probe_errno_ = 0;
  }
  close(fd);
  return aw_uring_probe_errno_ == 0;
#else
  return 0;
#endif
}

// The probe's verdict as an errno (0 = io_uring usable; see aw_have_uring).
int aw_uring_probe_errno(void) {
#if defined(__linux__)
  aw_have_uring();
  return aw_uring_probe_errno_;
#else
  return 38;  // ENOSYS
#endif
}

// Create a submission ring (or NULL — caller falls back). One ring per
// sender thread; rings are not thread-safe and never shared.
void* aw_uring_create(int entries) {
#if defined(__linux__)
  if (!aw_have_uring()) return nullptr;
  if (entries < 1) entries = 1;
  struct aw_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = uring_setup((unsigned)entries, &p);
  if (fd < 0) return nullptr;
  AwUring* r = new (std::nothrow) AwUring;
  if (!r) {
    close(fd);
    return nullptr;
  }
  memset(r, 0, sizeof(*r));
  r->ring_fd = fd;
  r->sq_entries = p.sq_entries;
  r->cq_entries = p.cq_entries;
  r->single_mmap = (p.features & kFeatSingleMmap) != 0;
  r->sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  r->cq_len = p.cq_off.cqes + p.cq_entries * sizeof(struct aw_uring_cqe);
  if (r->single_mmap && r->cq_len > r->sq_len) r->sq_len = r->cq_len;
  r->sq_ptr = mmap(nullptr, r->sq_len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, kOffSqRing);
  if (r->sq_ptr == MAP_FAILED) goto fail;
  if (r->single_mmap) {
    r->cq_ptr = r->sq_ptr;
    r->cq_len = r->sq_len;
  } else {
    r->cq_ptr = mmap(nullptr, r->cq_len, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, fd, kOffCqRing);
    if (r->cq_ptr == MAP_FAILED) {
      r->cq_ptr = nullptr;
      goto fail;
    }
  }
  r->sqes_len = p.sq_entries * sizeof(struct aw_uring_sqe);
  r->sqes = (struct aw_uring_sqe*)mmap(nullptr, r->sqes_len,
                                       PROT_READ | PROT_WRITE,
                                       MAP_SHARED | MAP_POPULATE, fd,
                                       kOffSqes);
  if (r->sqes == MAP_FAILED) {
    r->sqes = nullptr;
    goto fail;
  }
  {
    uint8_t* sq = (uint8_t*)r->sq_ptr;
    uint8_t* cq = (uint8_t*)r->cq_ptr;
    r->sq_head = (unsigned*)(sq + p.sq_off.head);
    r->sq_tail = (unsigned*)(sq + p.sq_off.tail);
    r->sq_mask = (unsigned*)(sq + p.sq_off.ring_mask);
    r->sq_array = (unsigned*)(sq + p.sq_off.array);
    r->cq_head = (unsigned*)(cq + p.cq_off.head);
    r->cq_tail = (unsigned*)(cq + p.cq_off.tail);
    r->cq_mask = (unsigned*)(cq + p.cq_off.ring_mask);
    r->cq_cqes = (struct aw_uring_cqe*)(cq + p.cq_off.cqes);
  }
  return r;
fail:
  if (r->sq_ptr && r->sq_ptr != MAP_FAILED) munmap(r->sq_ptr, r->sq_len);
  if (!r->single_mmap && r->cq_ptr) munmap(r->cq_ptr, r->cq_len);
  close(fd);
  delete r;
  return nullptr;
#else
  (void)entries;
  return nullptr;
#endif
}

void aw_uring_close(void* ring) {
#if defined(__linux__)
  if (!ring) return;
  AwUring* r = (AwUring*)ring;
  if (r->sqes) munmap(r->sqes, r->sqes_len);
  if (r->sq_ptr) munmap(r->sq_ptr, r->sq_len);
  if (!r->single_mmap && r->cq_ptr) munmap(r->cq_ptr, r->cq_len);
  close(r->ring_fd);
  delete r;
#else
  (void)ring;
#endif
}

// One burst, one ring submission: gather (bases, lens) into a single
// msghdr/SENDMSG SQE and wait for its completion. Returns bytes sent
// (short counts normal — the caller advances and re-enters), or -errno.
int64_t aw_uring_sendmsg(void* ring, int fd, const uint64_t* bases,
                         const int64_t* lens, int32_t niov) {
#if !defined(__linux__)
  (void)ring; (void)fd; (void)bases; (void)lens; (void)niov;
  return -38;  // ENOSYS
#else
  if (!ring) return -EINVAL;
  if (niov <= 0) return 0;
  AwUring* r = (AwUring*)ring;
  if (r->broken) return -EOPNOTSUPP;  // poisoned: caller latches off
  struct iovec iov[kMaxBatchIovs];
  int32_t n = niov < kMaxBatchIovs ? niov : kMaxBatchIovs;
  for (int32_t i = 0; i < n; ++i) {
    iov[i].iov_base = (void*)(uintptr_t)bases[i];
    iov[i].iov_len = (size_t)lens[i];
  }
  struct msghdr hdr;
  memset(&hdr, 0, sizeof(hdr));
  hdr.msg_iov = iov;
  hdr.msg_iovlen = n;
  unsigned tail = __atomic_load_n(r->sq_tail, __ATOMIC_RELAXED);
  unsigned idx = tail & *r->sq_mask;
  struct aw_uring_sqe* sqe = &r->sqes[idx];
  memset(sqe, 0, sizeof(*sqe));
  sqe->opcode = kOpSendmsg;
  sqe->fd = fd;
  sqe->addr = (uint64_t)(uintptr_t)&hdr;
  sqe->len = 1;
  // MSG_DONTWAIT is load-bearing twice over: a full socket buffer answers
  // -EAGAIN instead of parking this op in io-wq (where SO_SNDTIMEO does
  // not apply — a stalled peer would hang the sender thread in an
  // uninterruptible enter, defeating the caller's bounded-select pacing
  // and teardown joins), and a non-blocking op completes inline at
  // submit, so the completion wait below is one pass in practice.
  sqe->msg_flags = MSG_NOSIGNAL | MSG_DONTWAIT;
  sqe->user_data = tail;
  r->sq_array[idx] = idx;
  __atomic_store_n(r->sq_tail, tail + 1, __ATOMIC_RELEASE);
  // Submit + wait, retrying interrupted waits: the op references THIS
  // stack frame's msghdr/iov, so returning before its CQE is reaped
  // would leave the kernel reading dead stack AND make the Python
  // caller's retry duplicate bytes on the TCP stream. -EINTR before the
  // SQE was consumed re-enters with to_submit=1 (the ring still holds
  // it); after consumption a bare GETEVENTS wait suffices.
  for (;;) {
    int submitted = uring_enter(r->ring_fd, 1, 1, kEnterGetevents);
    if (submitted >= 0) break;
    unsigned sq_head = __atomic_load_n(r->sq_head, __ATOMIC_ACQUIRE);
    if (errno != EINTR) {
      if (sq_head == tail + 1) {
        // the SQE was consumed but its completion cannot be awaited:
        // the op may still reference this stack frame — poison the ring
        // so no later call can desync against the orphan
        r->broken = 1;
      } else {
        // not consumed: rewind our tail advance, or the NEXT call's
        // to_submit=1 would submit this call's stale SQE (whose iovecs
        // point at a dead stack frame) and misattribute its completion
        __atomic_store_n(r->sq_tail, tail, __ATOMIC_RELEASE);
      }
      return -(int64_t)errno;
    }
    if (sq_head == tail + 1) break;  // consumed: fall through to the wait
  }
  for (;;) {
    unsigned head = __atomic_load_n(r->cq_head, __ATOMIC_RELAXED);
    unsigned cq_tail = __atomic_load_n(r->cq_tail, __ATOMIC_ACQUIRE);
    if (head != cq_tail) {
      struct aw_uring_cqe* cqe = &r->cq_cqes[head & *r->cq_mask];
      int64_t res = cqe->res;
      __atomic_store_n(r->cq_head, head + 1, __ATOMIC_RELEASE);
      return res;  // >0 bytes, or the op's -errno (-EAGAIN = buffer full)
    }
    int w = uring_enter(r->ring_fd, 0, 1, kEnterGetevents);
    if (w < 0 && errno != EINTR) {
      r->broken = 1;  // op in flight, wait impossible: poison (see above)
      return -(int64_t)errno;
    }
  }
#endif
}

}  // extern "C"

extern "C" {

// Batch receive: fill up to nbufs buffers (one iovec each) in order.
// Returns total bytes read (a short tail buffer is normal on stream
// sockets), 0 on orderly EOF, or -errno when nothing was read.
int64_t aw_recvmmsg(int fd, const uint64_t* bases, const int64_t* lens,
                    int32_t nbufs, int32_t use_fallback) {
#if !AW_HAVE_SOCKETS
  (void)fd; (void)bases; (void)lens; (void)nbufs; (void)use_fallback;
  return -38;  // ENOSYS
#else
  if (nbufs <= 0) return 0;
#if defined(__linux__)
  // Some kernels/sandboxes (e.g. gVisor) implement recvmmsg but reject
  // MSG_WAITFORONE with EINVAL — a second RUNTIME probe, cached like the
  // ENOSYS one: first EINVAL answer routes every later call to the loop.
  static int waitforone_broken = 0;
  if (!use_fallback && !waitforone_broken && aw_have_sendmmsg()) {
    struct mmsghdr hdrs[kMaxBatchMsgs];
    struct iovec iov[kMaxBatchMsgs];
    int32_t n = nbufs < kMaxBatchMsgs ? nbufs : kMaxBatchMsgs;
    for (int32_t i = 0; i < n; ++i) {
      iov[i].iov_base = (void*)(uintptr_t)bases[i];
      iov[i].iov_len = (size_t)lens[i];
      memset(&hdrs[i], 0, sizeof(hdrs[i]));
      hdrs[i].msg_hdr.msg_iov = &iov[i];
      hdrs[i].msg_hdr.msg_iovlen = 1;
    }
    // MSG_WAITFORONE: block for the FIRST message only — on a blocking
    // socket a bare recvmmsg would otherwise wait for all n, hanging a
    // caller whose stream holds fewer bytes than the buffer set
    int r = recvmmsg(fd, hdrs, n, MSG_WAITFORONE, nullptr);
    if (r >= 0) {
      int64_t total = 0;
      for (int i = 0; i < r; ++i) total += (int64_t)hdrs[i].msg_len;
      return total;
    }
    if (errno != EINVAL) return -(int64_t)errno;
    waitforone_broken = 1;  // fall through to the recvmsg loop
  }
#endif  // __linux__
  int64_t total = 0;
  for (int32_t i = 0; i < nbufs; ++i) {
    struct iovec one;
    one.iov_base = (void*)(uintptr_t)bases[i];
    one.iov_len = (size_t)lens[i];
    struct msghdr hdr;
    memset(&hdr, 0, sizeof(hdr));
    hdr.msg_iov = &one;
    hdr.msg_iovlen = 1;
    // mirror MSG_WAITFORONE: only the first recv may block
    ssize_t got = recvmsg(fd, &hdr, i == 0 ? 0 : MSG_DONTWAIT);
    if (got < 0) {
      if (total > 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
        return total;
      return total > 0 ? total : -(int64_t)errno;
    }
    total += got;
    if (got < (ssize_t)one.iov_len) break;  // short read: stream drained
  }
  return total;
#endif  // AW_HAVE_SOCKETS
}

}  // extern "C"
