// Host data-plane wire hot loop: payload-frame header pack/unpack + checksum.
//
// The Python codec (control/wire.py) frames float payloads as
//   [tag u8][fields][count_word u32][checksum u32][payload bytes]
// where tag 2 = ScatterBlock (fields <iiiq>), tag 3 = ReduceBlock (<iiiqi>),
// the count word's top bit flags a float16 payload, and the checksum is the
// additive sum of the payload's LE u32 words mod 2^32, tail zero-padded
// (matches native.wire_checksum's numpy fallback exactly).
//
// These two entry points collapse the per-frame Python work — struct packs,
// bounds checks, and the full-payload checksum pass — into ONE ctypes call
// each way, so the per-byte cost of a payload frame is a single vectorized
// read (the checksum) with no intermediate allocation. Byte order is written
// explicitly little-endian so the wire format is host-independent.
//
// Compiled into the same .so as threshold_reduce.cpp (one loader, one ABI).

#include <cstdint>

namespace {

inline void put_le32(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)(v);
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

inline void put_le64(uint8_t* p, uint64_t v) {
  put_le32(p, (uint32_t)v);
  put_le32(p + 4, (uint32_t)(v >> 32));
}

inline uint32_t get_le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline uint64_t get_le64(const uint8_t* p) {
  return (uint64_t)get_le32(p) | ((uint64_t)get_le32(p + 4) << 32);
}

constexpr uint32_t kF16Flag = 0x80000000u;  // wire.py _F16_FLAG

}  // namespace

extern "C" {

// Additive payload checksum: sum of little-endian u32 words mod 2^32, the
// tail (payloads are always a multiple of 2 bytes) zero-padded. A word sum
// vectorizes to memory speed — one read pass, ~8x cheaper than the memcpy
// it replaces on the old join/readexactly path — and catches the framing
// corruptions the transport actually sees (truncation, garbage bodies).
uint32_t aw_checksum(const uint8_t* data, int64_t n) {
  int64_t n4 = n >> 2;
  uint64_t s = 0;
#pragma omp parallel for schedule(static) reduction(+ : s) if (n4 > 262144)
  for (int64_t i = 0; i < n4; ++i) {
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
    // memcpy load: one unaligned mov per word — decode checksums run at
    // payload offsets the dest-string length makes misaligned, where the
    // byte-shift form costs ~5x
    uint32_t w;
    __builtin_memcpy(&w, data + 4 * i, 4);
    s += w;
#else
    s += get_le32(data + 4 * i);
#endif
  }
  uint32_t tail = 0;
  for (int64_t i = n4 * 4, shift = 0; i < n; ++i, shift += 8)
    tail |= (uint32_t)data[i] << shift;
  return (uint32_t)(s + tail);
}

// Pack [tag][fields][count_word][checksum] for a payload frame and compute
// the checksum of `payload` in the same call. Returns the header length
// written into `out` (caller provides >= 34 bytes), or -1 on unknown tag.
int aw_pack_block(uint8_t* out, int tag, int32_t src_id, int32_t dest_id,
                  int32_t chunk_id, int64_t round_num, int32_t count,
                  const uint8_t* payload, int64_t payload_bytes,
                  uint32_t count_word) {
  if (tag != 2 && tag != 3) return -1;
  uint8_t* p = out;
  *p++ = (uint8_t)tag;
  put_le32(p, (uint32_t)src_id);
  put_le32(p + 4, (uint32_t)dest_id);
  put_le32(p + 8, (uint32_t)chunk_id);
  put_le64(p + 12, (uint64_t)round_num);
  p += 20;
  if (tag == 3) {
    put_le32(p, (uint32_t)count);
    p += 4;
  }
  put_le32(p, count_word);
  put_le32(p + 4, aw_checksum(payload, payload_bytes));
  p += 8;
  return (int)(p - out);
}

// Parse + verify a payload frame body starting at the tag byte. Fills
// out[0..6] = {src_id, dest_id, chunk_id, round_num, count, n_elems, is_f16}
// and returns the payload byte offset, or -1 (truncated) / -2 (checksum
// mismatch) / -3 (not a payload tag).
int64_t aw_unpack_block(const uint8_t* body, int64_t nbytes, int64_t* out) {
  if (nbytes < 1) return -1;
  int tag = body[0];
  if (tag != 2 && tag != 3) return -3;
  int64_t off = 1 + 20 + (tag == 3 ? 4 : 0) + 8;  // fields + count word + checksum
  if (nbytes < off) return -1;
  const uint8_t* p = body + 1;
  out[0] = (int32_t)get_le32(p);
  out[1] = (int32_t)get_le32(p + 4);
  out[2] = (int32_t)get_le32(p + 8);
  out[3] = (int64_t)get_le64(p + 12);
  out[4] = tag == 3 ? (int32_t)get_le32(p + 20) : 0;
  uint32_t count_word = get_le32(body + off - 8);
  uint32_t checksum = get_le32(body + off - 4);
  int is_f16 = (count_word & kF16Flag) != 0;
  int64_t n_elems = (int64_t)(count_word & ~kF16Flag);
  out[5] = n_elems;
  out[6] = is_f16;
  int64_t payload_bytes = n_elems * (is_f16 ? 2 : 4);
  if (payload_bytes > nbytes - off) return -1;
  if (aw_checksum(body + off, payload_bytes) != checksum) return -2;
  return off;
}

}  // extern "C"
