// Host-side data-plane kernels for the threshold allreduce engine.
//
// The reference's reduction executor is a JVM float loop in
// ScatteredDataBuffer.reduce (SURVEY.md §3 "Reduction executor"); on TPU the
// ICI path replaces it with XLA's compiled AllReduce, but the *host* data
// path — engine unit mode, the CPU fallback transport, and DCN-side chunk
// staging — still sums float chunks on the CPU. These are those loops,
// vectorized and OpenMP-parallel, exposed through a C ABI for ctypes
// (no pybind11 in this toolchain).
//
// Contract notes:
// - all arrays are dense float32/int32, C-contiguous (the Python side
//   guarantees this);
// - kernels parallelize across elements, so results are deterministic
//   (each output element is produced by exactly one thread).

#include <cstdint>
#include <cstring>

extern "C" {

// dst[i] += src[i].  ScatteredDataBuffer.store's accumulate.
void ar_accumulate(float* __restrict__ dst, const float* __restrict__ src, int64_t n) {
#pragma omp parallel for schedule(static) if (n > 65536)
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

// out[i] = counts[i] > 0 ? sum[i] / counts[i] : 0 — the consumer-side divide
// that turns (sum, count) into the partial average (SURVEY.md §3
// "Collective semantics").  In-place allowed (out == sum).
void ar_average(const float* __restrict__ sum, const int32_t* __restrict__ counts, float* __restrict__ out,
                int64_t n) {
#pragma omp parallel for schedule(static) if (n > 65536)
  for (int64_t i = 0; i < n; ++i) {
    out[i] = counts[i] > 0 ? sum[i] / static_cast<float>(counts[i]) : 0.0f;
  }
}

// Elastic-averaging apply (binder/elastic.py):
//   w[i] <- counts[i] > 0 ? (1 - a) * w[i] + a * sum[i] / counts[i] : w[i]
void ar_elastic_update(float* __restrict__ w, const float* __restrict__ sum, const int32_t* __restrict__ counts,
                       float alpha, int64_t n) {
  const float keep = 1.0f - alpha;
#pragma omp parallel for schedule(static) if (n > 65536)
  for (int64_t i = 0; i < n; ++i) {
    if (counts[i] > 0) {
      w[i] = keep * w[i] + alpha * (sum[i] / static_cast<float>(counts[i]));
    }
  }
}

// Expand per-chunk counts to per-element counts:
//   out[ chunk boundaries by lengths[c] ] = chunk_counts[c]
// ReducedDataBuffer.get_with_counts's repeat.
void ar_expand_counts(const int32_t* chunk_counts, const int64_t* lengths,
                      int64_t n_chunks, int32_t* out, int64_t n_out) {
  int64_t pos = 0;
  for (int64_t c = 0; c < n_chunks && pos < n_out; ++c) {
    int64_t len = lengths[c];
    if (len > n_out - pos) len = n_out - pos;
    for (int64_t i = 0; i < len; ++i) out[pos + i] = chunk_counts[c];
    pos += len;
  }
}

// v3: wire.cpp (payload-frame pack/unpack + checksum) joined the library.
int ar_abi_version() { return 5; }

}  // extern "C"
