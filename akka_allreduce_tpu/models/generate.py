"""Autoregressive decoding for the Transformer LM family (KV cache).

The reference is a training-side framework (gradient/weight sync —
SURVEY.md §1); inference is a beyond-parity surface that completes the LM
story the TPU way:

- the whole generation loop is ONE jitted program: prefill consumes the
  prompt in a single forward (filling every layer's KV cache), then a
  ``lax.scan`` emits one token per step — no per-token Python dispatch;
- the cache is shaped (B, max_len, H_kv, D) per layer, so grouped-query
  attention (``n_kv_heads``) shrinks the decode working set — the
  memory-bandwidth term that dominates small-batch decoding — by H/H_kv;
- sampling is greedy (``temperature=0``) or temperature-scaled
  categorical, with the key threaded through the scan carry.

Numerical oracle (tests/test_generate.py): teacher-forcing the decode path
over a fixed sequence must reproduce the training forward's logits at
every position — the cache is exact, not approximate.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from akka_allreduce_tpu.models.transformer import TransformerLM, tp_param_specs


@dataclasses.dataclass
class LMGenerator:
    """KV-cache decoder for a :class:`TransformerLM`'s trained params.

    Args:
      model: the TRAINING-configured module (its decode twin is derived;
        any training-time seq/tensor sharding in the config is ignored —
        the generator's own ``mesh`` decides the decode layout).
      max_len: cache capacity = prompt length + generated tokens budget.
        Must divide by the ``seq`` axis size when sequence-sharding.
      mesh: None = single device. A mesh with a ``model`` axis runs
        Megatron-style TENSOR-PARALLEL decode (VERDICT r3 #8): params
        shard per ``tp_param_specs``, each shard caches only its
        ``H_kv/tp`` heads (the KV cache — decode's bandwidth term —
        shards over the model axis; GQA already compacted it), and the
        out-projection psum completes each layer. A mesh with a ``seq``
        axis runs SEQUENCE-SHARDED decode (VERDICT r4 #5 — caches larger
        than one device): each shard owns ``max_len/sp`` contiguous cache
        SLOTS, scatter-writes the tokens it owns, and the shards' partial
        softmaxes merge split-K style
        (``ops.local_attention.seq_decode_attention``). The two compose
        on a ("seq", "model") mesh; an extra "data" axis is allowed and
        replicated. Prompts/tokens are replicated; logits come back
        identical on every shard (teacher-forced oracle in tests).
    """

    model: TransformerLM
    max_len: int
    cache_quant: str | None = None  # "int8": quantized KV cache (4x vs f32)
    #: jax Mesh for sharded decode: a "model" axis runs tensor-parallel
    #: decode, a "seq" axis shards the KV cache over its SLOTS
    #: (sequence-sharded decode, VERDICT r4 #5 — caches larger than one
    #: device), and a ("seq", "model") mesh composes both.
    mesh: object | None = None

    def __post_init__(self) -> None:
        base = dataclasses.replace(
            self.model, seq_axis=None, model_axis=None, tp_size=1
        )
        self.tp = 1
        self.sp = 1
        if self.mesh is not None:
            names = tuple(self.mesh.axis_names)
            if not set(names) <= {"data", "seq", "model"} or not (
                {"seq", "model"} & set(names)
            ):
                raise ValueError(
                    f"decode mesh needs a 'seq' and/or 'model' axis "
                    f"(plus an optional replicated 'data' axis), got "
                    f"{names}"
                )
            self.tp = (
                int(self.mesh.shape["model"]) if "model" in names else 1
            )
            self.sp = int(self.mesh.shape["seq"]) if "seq" in names else 1
            kv = (
                self.model.n_heads
                if self.model.n_kv_heads is None
                else self.model.n_kv_heads
            )
            # fail fast with the real constraint — otherwise the cache
            # device_put surfaces an opaque sharding-divisibility error
            if self.model.n_heads % self.tp or kv % self.tp:
                raise ValueError(
                    f"n_heads={self.model.n_heads} and n_kv_heads={kv} "
                    f"must both divide by the model axis size {self.tp} "
                    "for tensor-parallel decode"
                )
            if self.max_len % self.sp:
                raise ValueError(
                    f"max_len={self.max_len} must divide by the seq axis "
                    f"size {self.sp} (each shard owns max_len/sp cache "
                    "slots)"
                )
        self.decoder = dataclasses.replace(
            base, decode=True, max_decode_len=self.max_len,
            remat=False, cache_quant=self.cache_quant,
            model_axis="model" if self.tp > 1 else None,
            tp_size=self.tp,
            seq_axis="seq" if self.sp > 1 else None,
        )
        # the unsharded twin defines GLOBAL cache/param shapes; shard_map
        # in_specs slice them to each shard's local geometry
        self._global_decoder = dataclasses.replace(
            self.decoder, model_axis=None, tp_size=1, seq_axis=None
        )
        self._fns: dict = {}  # compiled generate loops, keyed by shape
        self._cache_tmpl: dict = {}  # zero-cache template per batch size

    def init_cache(self, batch: int) -> dict:
        """Fresh zero cache for ``batch`` rows (GLOBAL shapes under TP:
        (B, max_len, H_kv, D), sharded over the model axis on the head
        dim at apply time).

        ``init`` RUNS the module, so the cache it returns is dirty — index
        already advanced past the stub token, slot 0 filled from the
        throwaway init params; zero the whole tree (index included) to get
        the true empty-cache state. The traced init runs once per batch
        size (template cached); callers get fresh zeros each time."""
        if batch not in self._cache_tmpl:
            variables = self._global_decoder.init(
                jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
            )
            tmpl = variables["cache"]
            if self.tp > 1 or self.sp > 1:
                # shard the TEMPLATE once; zeros_like below then yields
                # already-sharded zeros with no per-call re-scatter
                tmpl = jax.device_put(
                    tmpl,
                    jax.tree.map(
                        lambda s: NamedSharding(self.mesh, s),
                        self._cache_specs(tmpl),
                        is_leaf=lambda x: isinstance(x, P),
                    ),
                )
            self._cache_tmpl[batch] = tmpl
        return jax.tree.map(jnp.zeros_like, self._cache_tmpl[batch])

    def _cache_specs(self, cache) -> dict:
        """PartitionSpec tree for the cache: K/V payloads ``cached_k/v``
        (B, L, H_kv, D) and int8 scales ``k/v_scale`` (B, L, H_kv) shard
        their SLOT dim over ``seq`` and their HEAD dim over ``model``
        (whichever of the two this generator's mesh carries);
        ``cache_index`` replicates.

        Keyed on the VARIABLE NAME, not leaf rank (ADVICE r4: a future
        cache variable with a coincidental ndim must not be silently
        mis-sharded) — an unknown name fails loudly here."""
        import jax.tree_util as jtu

        seq = "seq" if self.sp > 1 else None
        model = "model" if self.tp > 1 else None

        def spec(path, leaf):
            name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
            if name in ("cached_k", "cached_v"):
                return P(None, seq, model, None)
            if name in ("k_scale", "v_scale"):
                return P(None, seq, model)
            if name == "cache_index":
                return P()
            raise ValueError(
                f"unknown cache variable {name!r} (shape {leaf.shape}): "
                "add its decode-mesh PartitionSpec to LMGenerator."
                "_cache_specs before sharding it"
            )

        return jtu.tree_map_with_path(spec, cache)

    def place_params(self, params):
        """Shard FULL-shape trained params onto the decode mesh
        (``tp_param_specs`` layout — the same placement the TP trainers
        use); no-op without a mesh."""
        if self.tp == 1:
            return params
        specs = tp_param_specs(params, "model")
        return jax.device_put(
            params,
            jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P),
            ),
        )

    def _apply(self, params, cache, tokens):
        if self.tp > 1 or self.sp > 1:
            return self._apply_sharded(params, cache, tokens)
        logits, updated = self.decoder.apply(
            {"params": params["params"], "cache": cache},
            tokens,
            mutable=["cache"],
        )
        return logits, updated["cache"]

    def _apply_sharded(self, params, cache, tokens):
        """shard_map'd apply for TP (params + cache heads over ``model``)
        and/or sequence-sharded decode (cache slots over ``seq``; params
        replicated along it). Logits come back replicated either way (the
        TP out-psum / the seq split-K merge)."""
        if getattr(self, "_sharded_apply", None) is None:
            decoder = self.decoder
            p_specs = (
                tp_param_specs(params, "model") if self.tp > 1 else P()
            )
            c_specs = self._cache_specs(cache)

            def shard_apply(p, c, tok):
                logits, updated = decoder.apply(
                    {"params": p["params"], "cache": c},
                    tok,
                    mutable=["cache"],
                )
                return logits, updated["cache"]

            # jit(shard_map): eager shard_map would need a mesh context,
            # and the jit also caches the partitioned executable
            self._sharded_apply = jax.jit(
                jax.shard_map(
                    shard_apply,
                    mesh=self.mesh,
                    in_specs=(p_specs, c_specs, P()),
                    out_specs=(P(), c_specs),
                )
            )
        return self._sharded_apply(params, cache, jnp.asarray(tokens))

    def generate(
        self,
        params,
        prompt,
        steps: int,
        *,
        temperature: float = 0.0,
        seed: int = 0,
    ):
        """Generate ``steps`` tokens after ``prompt`` (B, T_prompt) int32.

        Returns (B, steps) int32. One jit per (prompt length, steps) pair;
        the scan body is compiled once regardless of ``steps``.
        """
        if prompt.ndim != 2:
            raise ValueError(f"prompt must be (B, T), got {prompt.shape}")
        if steps < 1:
            raise ValueError(f"need steps >= 1, got {steps}")
        if prompt.shape[1] + steps > self.max_len:
            raise ValueError(
                f"prompt {prompt.shape[1]} + steps {steps} exceeds "
                f"max_len {self.max_len}"
            )
        cache = self.init_cache(prompt.shape[0])
        key = (tuple(prompt.shape), steps, float(temperature))
        if key not in self._fns:
            self._fns[key] = self._compiled(steps, float(temperature))
        fn = self._fns[key]
        return fn(params, cache, jnp.asarray(prompt), jax.random.PRNGKey(seed))

    def _compiled(self, steps: int, temperature: float):
        apply = self._apply

        def sample(logits, key):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                key, logits / temperature, axis=-1
            ).astype(jnp.int32)

        def run(params, cache, prompt, key):
            # prefill: the whole prompt in one forward fills the cache
            logits, cache = apply(params, cache, prompt)
            k0, key = jax.random.split(key)
            tok = sample(logits[:, -1], k0)

            def step(carry, _):
                cache, tok, key = carry
                logits, cache = apply(params, cache, tok[:, None])
                k, key = jax.random.split(key)
                nxt = sample(logits[:, -1], k)
                return (cache, nxt, key), tok

            (_, last, _), out = jax.lax.scan(
                step, (cache, tok, key), None, length=steps - 1
            )
            # out is (steps-1, B): tokens emitted BEFORE each scan step
            return jnp.concatenate(
                [jnp.swapaxes(out, 0, 1), last[:, None]], axis=1
            )

        return jax.jit(run)

    def decode_logits(self, params, tokens, *, chunk: int = 1):
        """Teacher-forced logits via the cache path: feed ``tokens``
        (B, T) in ``chunk``-sized pieces and return (B, T, vocab) — the
        oracle hook: must equal the training forward's logits."""
        b, t = tokens.shape
        if t % chunk:
            raise ValueError(f"{t=} not divisible by {chunk=}")
        cache = self.init_cache(b)
        outs = []
        for i in range(0, t, chunk):
            logits, cache = self._apply(
                params, cache, jnp.asarray(tokens[:, i : i + chunk])
            )
            outs.append(logits)
        return jnp.concatenate(outs, axis=1)
