"""Autoregressive decoding for the Transformer LM family (KV cache).

The reference is a training-side framework (gradient/weight sync —
SURVEY.md §1); inference is a beyond-parity surface that completes the LM
story the TPU way:

- the whole generation loop is ONE jitted program: prefill consumes the
  prompt in a single forward (filling every layer's KV cache), then a
  ``lax.scan`` emits one token per step — no per-token Python dispatch;
- the cache is shaped (B, max_len, H_kv, D) per layer, so grouped-query
  attention (``n_kv_heads``) shrinks the decode working set — the
  memory-bandwidth term that dominates small-batch decoding — by H/H_kv;
- sampling is greedy (``temperature=0``) or temperature-scaled
  categorical, with the key threaded through the scan carry.

Numerical oracle (tests/test_generate.py): teacher-forcing the decode path
over a fixed sequence must reproduce the training forward's logits at
every position — the cache is exact, not approximate.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from akka_allreduce_tpu.models.transformer import TransformerLM


@dataclasses.dataclass
class LMGenerator:
    """KV-cache decoder for a :class:`TransformerLM`'s trained params.

    Args:
      model: the TRAINING-configured module (its decode twin is derived;
        seq/tensor sharding must be off — decode is single-device).
      max_len: cache capacity = prompt length + generated tokens budget.
    """

    model: TransformerLM
    max_len: int
    cache_quant: str | None = None  # "int8": quantized KV cache (4x vs f32)

    def __post_init__(self) -> None:
        if self.model.seq_axis is not None or self.model.tp_size > 1:
            raise ValueError(
                "decoding runs single-device: build the generator from an "
                "unsharded model config (seq_axis=None, tp_size=1)"
            )
        self.decoder = dataclasses.replace(
            self.model, decode=True, max_decode_len=self.max_len,
            remat=False, cache_quant=self.cache_quant,
        )
        self._fns: dict = {}  # compiled generate loops, keyed by shape
        self._cache_tmpl: dict = {}  # zero-cache template per batch size

    def init_cache(self, batch: int) -> dict:
        """Fresh zero cache for ``batch`` rows.

        ``init`` RUNS the module, so the cache it returns is dirty — index
        already advanced past the stub token, slot 0 filled from the
        throwaway init params; zero the whole tree (index included) to get
        the true empty-cache state. The traced init runs once per batch
        size (template cached); callers get fresh zeros each time."""
        if batch not in self._cache_tmpl:
            variables = self.decoder.init(
                jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
            )
            self._cache_tmpl[batch] = variables["cache"]
        return jax.tree.map(jnp.zeros_like, self._cache_tmpl[batch])

    def _apply(self, params, cache, tokens):
        logits, updated = self.decoder.apply(
            {"params": params["params"], "cache": cache},
            tokens,
            mutable=["cache"],
        )
        return logits, updated["cache"]

    def generate(
        self,
        params,
        prompt,
        steps: int,
        *,
        temperature: float = 0.0,
        seed: int = 0,
    ):
        """Generate ``steps`` tokens after ``prompt`` (B, T_prompt) int32.

        Returns (B, steps) int32. One jit per (prompt length, steps) pair;
        the scan body is compiled once regardless of ``steps``.
        """
        if prompt.ndim != 2:
            raise ValueError(f"prompt must be (B, T), got {prompt.shape}")
        if steps < 1:
            raise ValueError(f"need steps >= 1, got {steps}")
        if prompt.shape[1] + steps > self.max_len:
            raise ValueError(
                f"prompt {prompt.shape[1]} + steps {steps} exceeds "
                f"max_len {self.max_len}"
            )
        cache = self.init_cache(prompt.shape[0])
        key = (tuple(prompt.shape), steps, float(temperature))
        if key not in self._fns:
            self._fns[key] = self._compiled(steps, float(temperature))
        fn = self._fns[key]
        return fn(params, cache, jnp.asarray(prompt), jax.random.PRNGKey(seed))

    def _compiled(self, steps: int, temperature: float):
        apply = self._apply

        def sample(logits, key):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                key, logits / temperature, axis=-1
            ).astype(jnp.int32)

        def run(params, cache, prompt, key):
            # prefill: the whole prompt in one forward fills the cache
            logits, cache = apply(params, cache, prompt)
            k0, key = jax.random.split(key)
            tok = sample(logits[:, -1], k0)

            def step(carry, _):
                cache, tok, key = carry
                logits, cache = apply(params, cache, tok[:, None])
                k, key = jax.random.split(key)
                nxt = sample(logits[:, -1], k)
                return (cache, nxt, key), tok

            (_, last, _), out = jax.lax.scan(
                step, (cache, tok, key), None, length=steps - 1
            )
            # out is (steps-1, B): tokens emitted BEFORE each scan step
            return jnp.concatenate(
                [jnp.swapaxes(out, 0, 1), last[:, None]], axis=1
            )

        return jax.jit(run)

    def decode_logits(self, params, tokens, *, chunk: int = 1):
        """Teacher-forced logits via the cache path: feed ``tokens``
        (B, T) in ``chunk``-sized pieces and return (B, T, vocab) — the
        oracle hook: must equal the training forward's logits."""
        b, t = tokens.shape
        if t % chunk:
            raise ValueError(f"{t=} not divisible by {chunk=}")
        cache = self.init_cache(b)
        outs = []
        for i in range(0, t, chunk):
            logits, cache = self._apply(
                params, cache, jnp.asarray(tokens[:, i : i + chunk])
            )
            outs.append(logits)
        return jnp.concatenate(outs, axis=1)
