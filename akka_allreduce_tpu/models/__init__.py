"""Model families for the benchmark workloads (BASELINE.json:9-10).

The reference trains BIDMach learners (MLP on MNIST; ResNet-50 gradient sync,
SURVEY.md §2 L5). Here the equivalents are flax modules designed TPU-first:
NHWC layouts, bfloat16-friendly compute with fp32 parameters, and
normalization that is pure-functional under SPMD.
"""

from akka_allreduce_tpu.models.mlp import MLP  # noqa: F401
from akka_allreduce_tpu.models.resnet import ResNet50, ResNet  # noqa: F401
from akka_allreduce_tpu.models.transformer import TransformerLM  # noqa: F401
from akka_allreduce_tpu.models.generate import LMGenerator  # noqa: F401
from akka_allreduce_tpu.models import data  # noqa: F401
