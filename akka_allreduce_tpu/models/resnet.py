"""ResNet-50 for the gradient-sync workload (BASELINE.json:10: 25M-param
chunked buffer, ring schedule).

TPU-first choices:
- NHWC layout (XLA's native conv layout on TPU).
- GroupNorm instead of BatchNorm: normalization is then a pure function of the
  batch shard, so the train step stays stateless under ``shard_map`` and no
  cross-device statistics sync competes with the gradient collective. Param
  count stays ~25.6M, matching the reference workload's buffer size.
- bf16 compute / fp32 params when ``compute_dtype=jnp.bfloat16`` (MXU-friendly).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    groups: int = 32
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(
            self.features, (1, 1), use_bias=False, dtype=self.compute_dtype
        )(x)
        y = nn.GroupNorm(num_groups=min(self.groups, self.features))(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.features,
            (3, 3),
            strides=(self.strides, self.strides),
            padding="SAME",
            use_bias=False,
            dtype=self.compute_dtype,
        )(y)
        y = nn.GroupNorm(num_groups=min(self.groups, self.features))(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.features * 4, (1, 1), use_bias=False, dtype=self.compute_dtype
        )(y)
        y = nn.GroupNorm(num_groups=min(self.groups, self.features * 4))(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features * 4,
                (1, 1),
                strides=(self.strides, self.strides),
                use_bias=False,
                dtype=self.compute_dtype,
            )(residual)
            residual = nn.GroupNorm(
                num_groups=min(self.groups, self.features * 4)
            )(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Bottleneck ResNet; stage_sizes (3,4,6,3) is ResNet-50."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    classes: int = 1000
    width: int = 64
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(
            self.width,
            (7, 7),
            strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=self.compute_dtype,
        )(x)
        x = nn.GroupNorm(num_groups=min(32, self.width))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            features = self.width * (2**stage)
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(
                    features,
                    strides=strides,
                    compute_dtype=self.compute_dtype,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.classes, dtype=jnp.float32)(x)
        return x


def ResNet50(classes: int = 1000, compute_dtype=jnp.float32) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3), classes=classes, compute_dtype=compute_dtype
    )


def resnet_fwd_flops(
    model: ResNet, image_size: int, batch: int = 1
) -> float:
    """Analytic forward matmul/conv FLOPs of one batch through ``model`` at
    ``image_size`` x ``image_size`` inputs (2 FLOPs per MAC; norms and
    elementwise ops excluded — they are not MXU work). Multiply by 3 for a
    train step. Mirrors ``ResNet.__call__``'s architecture exactly so the
    MFU accounting (utils/benchmarking.py) never needs an XLA compile.
    """
    total = 0.0

    def conv(cin, cout, k, h, w):
        nonlocal total
        total += 2.0 * k * k * cin * cout * h * w * batch

    h = -(-image_size // 2)  # stem 7x7 stride 2, SAME-ish padding
    conv(3, model.width, 7, h, h)
    h = -(-h // 2)  # maxpool stride 2
    cin = model.width
    for stage, n_blocks in enumerate(model.stage_sizes):
        f = model.width * (2**stage)
        for block in range(n_blocks):
            stride = 2 if stage > 0 and block == 0 else 1
            h_out = -(-h // stride)
            conv(cin, f, 1, h, h)  # 1x1 reduce (input spatial)
            conv(f, f, 3, h_out, h_out)  # 3x3 (strided)
            conv(f, 4 * f, 1, h_out, h_out)  # 1x1 expand
            if cin != 4 * f or stride != 1:
                conv(cin, 4 * f, 1, h_out, h_out)  # projection shortcut
            cin = 4 * f
            h = h_out
    total += 2.0 * cin * model.classes * batch  # final Dense
    return total
