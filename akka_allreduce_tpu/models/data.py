"""Synthetic dataset generators (no network access in this environment).

Deterministic, learnable streams shaped like the benchmark datasets: each class
has a fixed random template; samples are template + noise, so a correct DP
trainer demonstrably reduces loss and the multi-device run can be compared
step-for-step against a single-device oracle on identical batches.
"""

from __future__ import annotations

import numpy as np


class SyntheticClassification:
    """Class-template + Gaussian-noise stream with a fixed seed."""

    def __init__(
        self,
        input_shape: tuple[int, ...],
        classes: int,
        *,
        noise: float = 0.5,
        seed: int = 0,
    ) -> None:
        self.input_shape = input_shape
        self.classes = classes
        self.noise = noise
        rng = np.random.default_rng(seed)
        self.templates = rng.standard_normal(
            (classes, *input_shape), dtype=np.float32
        )
        self._seed = seed

    def batches(self, batch_size: int, steps: int, *, seed_offset: int = 1):
        """Yield ``steps`` batches of (images, labels), deterministically."""
        rng = np.random.default_rng(self._seed + seed_offset)
        for _ in range(steps):
            labels = rng.integers(0, self.classes, size=batch_size)
            noise = rng.standard_normal(
                (batch_size, *self.input_shape), dtype=np.float32
            )
            images = self.templates[labels] + self.noise * noise
            yield images, labels.astype(np.int32)

    # host template tensors up to this size are uploaded so the device
    # sampler presents the IDENTICAL task to the host batches() stream
    _UPLOAD_MAX_BYTES = 32 * 1024 * 1024

    def device_sampler(self):
        """A traced ``(key, batch_size) -> (x, y)`` drawing batches on device
        — the data-loader path that keeps training loops free of host->device
        transfers (each DP device draws its own shard inside the jitted step;
        see ``DPTrainer.train_chain``).

        Small template tensors (MNIST-scale) are uploaded once, so the device
        stream and the host ``batches()`` stream share the exact same task —
        checkpoints and chains mix freely. At ImageNet scale the host tensor
        is ~600 MB (minutes over a slow host<->device link), so templates are
        regenerated ON DEVICE from the dataset seed instead: same structure,
        different template values. That divergence is flagged on the returned
        function as ``diverges_from_host_stream`` so callers mixing the two
        paths (e.g. resuming a host-loop checkpoint with --device-data) can
        warn.
        """
        import jax
        import jax.numpy as jnp

        noise_scale = self.noise
        classes = self.classes
        shape = self.input_shape
        diverges = self.templates.nbytes > self._UPLOAD_MAX_BYTES
        if diverges:
            # eager device-side generation, ONCE (a closure constant of the
            # jitted chain) — never inside the per-step scan body
            templates = jax.jit(
                lambda: jax.random.normal(
                    jax.random.PRNGKey(self._seed),
                    (classes, *shape),
                    jnp.float32,
                )
            )()
        else:
            templates = jnp.asarray(self.templates)

        def sample(key, batch_size: int):
            kl, kn = jax.random.split(key)
            labels = jax.random.randint(kl, (batch_size,), 0, classes)
            x = templates[labels] + noise_scale * jax.random.normal(
                kn, (batch_size, *shape), dtype=jnp.float32
            )
            return x, labels.astype(jnp.int32)

        sample.diverges_from_host_stream = diverges
        return sample


class SyntheticCopyLM:
    """Long-range-dependency LM stream: the second half of every sequence
    repeats the first half, so next-token loss on the back half is only
    learnable by attending ``seq_len/2`` tokens back — across sequence-shard
    boundaries under context parallelism. Perfect for validating that ring
    attention / Ulysses actually carry information over the ICI ring."""

    def __init__(self, seq_len: int, vocab: int = 64, *, seed: int = 0) -> None:
        if seq_len % 2:
            raise ValueError(f"seq_len must be even, got {seq_len}")
        self.seq_len = seq_len
        self.vocab = vocab
        self._seed = seed

    def batches(self, batch_size: int, steps: int, *, seed_offset: int = 1):
        """Yield ``steps`` batches of (inputs, labels), each (B, seq_len)."""
        rng = np.random.default_rng(self._seed + seed_offset)
        half = self.seq_len // 2
        for _ in range(steps):
            first = rng.integers(
                0, self.vocab, size=(batch_size, half + 1), dtype=np.int64
            )
            seq = np.concatenate([first, first[:, 1:]], axis=1)  # len + 1
            yield seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)

    def device_sampler(self):
        """Traced ``(key, batch_rows) -> (tokens, labels)`` of GLOBAL
        sequences, drawn on device. Under sequence parallelism every seq
        shard of a replica row must agree on the row's data, so the chain
        gives all shards of a row the same key and each slices its own
        ``T_local`` columns (``LongContextTrainer.train_chain``)."""
        import jax
        import jax.numpy as jnp

        half = self.seq_len // 2
        vocab = self.vocab

        def sample(key, batch_rows: int):
            first = jax.random.randint(
                key, (batch_rows, half + 1), 0, vocab, dtype=jnp.int32
            )
            seq = jnp.concatenate([first, first[:, 1:]], axis=1)
            return seq[:, :-1], seq[:, 1:]

        return sample


class FileDataset:
    """File-backed dataset seam (VERDICT r4 #8): a ``.npz`` file or a
    directory of ``.npz`` shards, each holding arrays ``x`` and ``y`` —
    the drop-in replacement for the synthetic streams when an environment
    HAS real data (this one has no network access, so every built-in
    workload is a synthetic shape-faithful stand-in; see PARITY.md
    "Workloads").

    API-compatible with the synthetic generators: ``batches`` yields
    deterministic shuffled minibatches (reshuffling each pass through the
    data), ``device_sampler`` uploads the arrays once and draws batches
    on device inside the jitted chain. Labels are cast to int32; inputs
    keep their stored dtype (f32 images, int32 tokens — whatever the
    trainer's placement expects).
    """

    def __init__(self, path, *, x_key: str = "x", y_key: str = "y",
                 seed: int = 0) -> None:
        from pathlib import Path

        p = Path(path)
        files = sorted(p.glob("*.npz")) if p.is_dir() else [p]
        if not files:
            raise FileNotFoundError(f"no .npz shards under {p}")
        xs, ys = [], []
        for f in files:
            with np.load(f, allow_pickle=False) as z:
                if x_key not in z or y_key not in z:
                    raise KeyError(
                        f"{f} lacks arrays {x_key!r}/{y_key!r} "
                        f"(has {sorted(z.files)})"
                    )
                xs.append(np.asarray(z[x_key]))
                ys.append(np.asarray(z[y_key]))
        self.x = np.concatenate(xs, axis=0)
        self.y = np.concatenate(ys, axis=0).astype(np.int32)
        if self.x.shape[0] != self.y.shape[0]:
            raise ValueError(
                f"x rows {self.x.shape[0]} != y rows {self.y.shape[0]}"
            )
        self.n = self.x.shape[0]
        self._seed = seed

    def batches(self, batch_size: int, steps: int, *, seed_offset: int = 1):
        """Yield ``steps`` minibatches, shuffling on every pass through
        the data (sampling without replacement within a pass).

        Misuse fails EAGERLY at call time — a plain generator would defer
        the check to the first ``next()``, and a ``steps=0`` call would
        never validate at all (ADVICE r5)."""
        if batch_size > self.n:
            raise ValueError(
                f"batch {batch_size} exceeds dataset rows {self.n}"
            )

        def gen():
            rng = np.random.default_rng(self._seed + seed_offset)
            order = rng.permutation(self.n)
            at = 0
            for _ in range(steps):
                if at + batch_size > self.n:
                    order = rng.permutation(self.n)
                    at = 0
                idx = order[at : at + batch_size]
                at += batch_size
                yield self.x[idx], self.y[idx]

        return gen()

    def device_sampler(self):
        """Traced ``(key, batch_size) -> (x, y)`` sampling rows (with
        replacement) from the on-device copy of the arrays — the zero
        host-I/O path of the synthetic samplers, for real data."""
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(self.x)
        y = jnp.asarray(self.y)
        n = self.n

        def sample(key, batch_size: int):
            idx = jax.random.randint(key, (batch_size,), 0, n)
            return x[idx], y[idx]

        return sample


def lm_copy_task(seq_len: int = 128, vocab: int = 64, seed: int = 0) -> SyntheticCopyLM:
    """The long-context LM workload (no analog in the reference — SURVEY.md §6)."""
    return SyntheticCopyLM(seq_len, vocab, seed=seed)


def mnist_like(seed: int = 0) -> SyntheticClassification:
    """28x28x1, 10 classes — the MLP/MNIST workload shape (BASELINE.json:9)."""
    return SyntheticClassification((28, 28, 1), 10, seed=seed)


def imagenet_like(
    size: int = 64, classes: int = 1000, seed: int = 0
) -> SyntheticClassification:
    """NHWC images for the ResNet-50 workload (reduced spatial size by default
    so tests and the single-chip bench stay fast; 224 for full-fidelity runs)."""
    return SyntheticClassification((size, size, 3), classes, seed=seed)
