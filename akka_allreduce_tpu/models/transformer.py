"""Decoder-only Transformer LM with pluggable sequence/context parallelism.

A model family the reference does not have (its workloads stop at MLP and
ResNet-50 — SURVEY.md §2 L5); it exists here because long-context is
first-class in the TPU rebuild. Designed TPU-first:

- all heavy math is batched matmul (MXU-shaped), optional bfloat16 compute
  with fp32 params/logits;
- rotary position embeddings, so a sequence-sharded device needs only its
  integer global offset — no position-table gather crossing shards;
- attention dispatches on ``seq_axis``: ``None`` -> dense single-device;
  otherwise ring attention or Ulysses all-to-all over that mesh axis
  (ops/ring_attention.py), making the SAME module runnable under ``shard_map``
  with the sequence dimension sharded across the ICI ring. The shard count is
  read from the mesh itself (``lax.axis_size``), so the module cannot drift
  out of sync with the mesh it runs under.

When ``seq_axis`` is set the module must be applied inside ``shard_map`` with
that axis in scope; ``__call__`` then takes this device's (B, T_local) token
shard.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from akka_allreduce_tpu.ops.ring_attention import (
    attention_reference,
    ring_attention,
    ulysses_attention,
)


def rope(x: jax.Array, offset: jax.Array | int, *, base: float = 10000.0):
    """Rotary embedding over the last (even) dim; positions = offset + arange(T).

    ``x``: (B, T, H, D). Pure elementwise after a cos/sin table build, so XLA
    fuses it into the surrounding projections.

    The ANGLES (position · frequency) and the trig tables are always
    computed in float32 — position precision is what long-context rope
    depends on — but the elementwise rotation runs in ``x``'s own dtype:
    under bf16 compute the (B, T, H, D) tensors would otherwise make four
    f32 round trips per projection, a measured ~2.8 ms/step of pure cast
    traffic at the MoE bench shape (BENCHMARKS.md round 4).
    """
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"rope needs an even head dim, got {d}")
    pos = offset + jnp.arange(x.shape[1])
    freqs = base ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]  # (T, D/2)
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate(
        (x1 * cos - x2 * sin, x1 * sin + x2 * cos), axis=-1
    )


class Attention(nn.Module):
    """Causal multi-head self-attention with RoPE, SP and TP dispatch.

    Tensor parallelism (``model_axis``/``tp_size``): each shard projects and
    attends ``n_heads / tp_size`` heads (the kernels' head dims are the
    sharded dims), the out-projection produces a partial sum, and ONE psum
    over ``model_axis`` completes it — Megatron-style column/row split, with
    the output bias added AFTER the psum so it is applied exactly once.

    Grouped-query attention (``n_kv_heads`` < ``n_heads``; 1 = MQA): K/V
    project to ``n_kv_heads`` heads and stay COMPACT until the compute
    site — under ring attention the ppermute wire bytes shrink by
    H/H_kv, under Ulysses the K/V all_to_all does (ops/ring_attention.py).

    Autoregressive decoding (``decode=True``): a "cache" variable
    collection holds the K/V written so far — shaped
    (B, max_decode_len, H_kv, D), so GQA shrinks the cache (its main
    inference win) — and each call appends its chunk at the running
    ``cache_index`` and attends over the whole cache causally. Init the
    cache with ``model.init`` on any-length tokens; apply with
    ``mutable=["cache"]``. Composes with tensor parallelism (each model
    shard caches its kv_local heads — run inside shard_map over the
    ``model`` axis) AND with sequence sharding (``seq_axis`` set while
    decoding: each seq shard owns a contiguous ``max_decode_len / n``
    slice of the cache SLOTS, writes scatter to the owning shard, and
    attention merges the shards' partial softmaxes split-K style over the
    axis — ``ops.local_attention.seq_decode_attention``; VERDICT r4 #5).

    ``cache_quant="int8"`` stores the cache quantized per (token, head)
    row — int8 payload + one f32 scale per row, ~4× fewer cache bytes
    than f32 (2× vs bf16) at ~0.4 % per-element quantization error — the
    inference twin of the training wire's int8 ring compression.
    """

    n_heads: int
    n_kv_heads: int | None = None  # None = n_heads (standard MHA)
    seq_axis: str | None = None
    seq_impl: str = "ring"  # "ring" | "ulysses"
    compute_dtype: jnp.dtype = jnp.float32
    model_axis: str | None = None
    tp_size: int = 1
    decode: bool = False  # KV-cache autoregressive mode
    max_decode_len: int = 0  # cache capacity (decode=True only)
    cache_quant: str | None = None  # None = compute dtype; "int8" quantized

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        if d_model % self.n_heads:
            raise ValueError(f"{d_model=} not divisible by {self.n_heads=}")
        if self.n_heads % self.tp_size:
            raise ValueError(f"{self.n_heads=} not divisible by {self.tp_size=}")
        kv_heads = (
            self.n_heads if self.n_kv_heads is None else self.n_kv_heads
        )
        if kv_heads < 1:
            raise ValueError(f"n_kv_heads must be >= 1, got {kv_heads}")
        if self.n_heads % kv_heads:
            raise ValueError(
                f"{self.n_heads=} not divisible by n_kv_heads={kv_heads}"
            )
        if kv_heads % self.tp_size:
            raise ValueError(
                f"n_kv_heads={kv_heads} not divisible by {self.tp_size=}"
            )
        if self.decode and self.max_decode_len < 1:
            raise ValueError("decode=True needs max_decode_len >= 1")
        head = d_model // self.n_heads
        heads_local = self.n_heads // self.tp_size
        kv_local = kv_heads // self.tp_size
        dense = lambda name, hh: nn.DenseGeneral(  # noqa: E731
            (hh, head),
            dtype=self.compute_dtype,
            name=name,
        )
        q = dense("q", heads_local)(x)
        k = dense("k", kv_local)(x)
        v = dense("v", kv_local)(x)

        if self.decode:
            if self.cache_quant not in (None, "int8"):
                raise ValueError(
                    f"cache_quant must be None or 'int8', got "
                    f"{self.cache_quant!r}"
                )
            quant = self.cache_quant == "int8"
            b, t = x.shape[0], x.shape[1]
            if self.seq_axis is not None:
                # SEQUENCE-SHARDED cache (VERDICT r4 #5): each shard of
                # the seq axis owns a contiguous L/n_sh slice of the cache
                # slots; decode attention merges the shards' partial
                # softmaxes split-K style (seq_decode_attention). Composes
                # with TP (heads shard on model, slots on seq).
                n_sh = lax.axis_size(self.seq_axis)
                if self.max_decode_len % n_sh:
                    raise ValueError(
                        f"max_decode_len={self.max_decode_len} not "
                        f"divisible by the {n_sh}-shard seq axis"
                    )
                l_local = self.max_decode_len // n_sh
                k_off = lax.axis_index(self.seq_axis) * l_local
            else:
                l_local = self.max_decode_len
                k_off = 0
            kv_shape = (b, l_local, kv_local, head)
            cache_dt = jnp.int8 if quant else k.dtype
            ck = self.variable("cache", "cached_k", jnp.zeros, kv_shape, cache_dt)
            cv = self.variable("cache", "cached_v", jnp.zeros, kv_shape, cache_dt)
            if quant:
                cks = self.variable(
                    "cache", "k_scale", jnp.zeros, kv_shape[:3], jnp.float32
                )
                cvs = self.variable(
                    "cache", "v_scale", jnp.zeros, kv_shape[:3], jnp.float32
                )
            ci = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            offset = ci.value  # global position of this chunk's first token
        elif self.seq_axis is None:
            offset = 0
        else:
            offset = lax.axis_index(self.seq_axis) * x.shape[1]
        q, k = rope(q, offset), rope(k, offset)

        if self.decode:
            from akka_allreduce_tpu.ops.local_attention import (
                _DENSE_MAX_T,
                local_attention,
                quantized_cache_attention,
                seq_decode_attention,
            )

            # append this chunk's K/V at the running index; slots past
            # offset + t hold zeros and are causally invisible (their
            # k_pos exceeds every live q_pos)
            if self.seq_axis is not None:
                # scatter each token to the shard that owns its slot:
                # indices outside this shard's [k_off, k_off + l_local)
                # range are clamped to l_local and DROPPED by the scatter
                pos = offset + jnp.arange(t) - k_off
                idx = jnp.where((pos >= 0) & (pos < l_local), pos, l_local)

                def write(cache, chunk):
                    cache.value = cache.value.at[:, idx].set(
                        chunk, mode="drop"
                    )

            else:

                def write(cache, chunk):
                    cache.value = lax.dynamic_update_slice(
                        cache.value,
                        chunk,
                        (0, offset) + (0,) * (chunk.ndim - 2),
                    )

            if quant:
                def quantize(x_):
                    # per (token, head) row: one f32 scale over the D dim
                    s = jnp.max(jnp.abs(x_), axis=-1) / 127.0
                    s = jnp.maximum(s, 1e-8).astype(jnp.float32)
                    q_ = jnp.clip(
                        jnp.round(x_ / s[..., None].astype(x_.dtype)),
                        -127, 127,
                    ).astype(jnp.int8)
                    return q_, s

                kq, ks = quantize(k)
                vq, vs = quantize(v)
                write(ck, kq), write(cv, vq)
                write(cks, ks), write(cvs, vs)
                ci.value = offset + t
                # decode (small Tq over the long cache): attend directly
                # over the int8 payloads — the scales fold into the
                # scores/weights, so no dequantized full-precision copy of
                # the cache is ever materialized (the bandwidth the
                # quantization was bought for). Prefill (large Tq) would
                # make the dense (B,H,Tq,L) f32 scores the memory hog
                # instead; there, dequantize once and take
                # local_attention's blockwise/flash dispatch. Gate on the
                # per-key byte costs of the two branches (both scale with
                # L, so L cancels): fused scores cost 4·H·Tq bytes/key,
                # dequant costs itemsize·2·H_kv·D bytes/key (K and V) —
                # Tq=1 over any cache length stays fused.
                score_b = 4 * heads_local * t
                dequant_b = 2 * kv_local * head * k.dtype.itemsize
                # t == 1 is unconditional: the dequant branch would also
                # WRITE and re-read the full-precision copy (its per-key
                # cost is ~3x dequant_b in practice), so token-by-token
                # decode must never take it even at extreme GQA ratios
                # where the byte model above tips the other way
                if self.seq_axis is not None:
                    # sharded cache: local partial over this shard's slots
                    # (scales fold in, like quantized_cache_attention),
                    # split-K merge over the seq axis; under TP the inputs
                    # are ALSO model-varying — the blockwise carry must
                    # carry that typing
                    out = seq_decode_attention(
                        q, ck.value, cv.value, self.seq_axis,
                        q_offset=offset, k_offset=k_off,
                        k_scale=cks.value, v_scale=cvs.value,
                        extra_vary_axes=(
                            (self.model_axis,) if self.model_axis else ()
                        ),
                    )
                elif (
                    t == 1
                    or score_b <= dequant_b
                    or t * self.max_decode_len <= _DENSE_MAX_T * _DENSE_MAX_T
                ):
                    out = quantized_cache_attention(
                        q, ck.value, cks.value, cv.value, cvs.value,
                        q_offset=offset,
                    )
                else:
                    dq = lambda c, s: (  # noqa: E731
                        c.value.astype(k.dtype)
                        * s.value[..., None].astype(k.dtype)
                    )
                    out = local_attention(
                        q, dq(ck, cks), dq(cv, cvs),
                        causal=True, q_offset=offset,
                    )
            else:
                write(ck, k), write(cv, v)
                ci.value = offset + t
                if self.seq_axis is not None:
                    out = seq_decode_attention(
                        q, ck.value, cv.value, self.seq_axis,
                        q_offset=offset, k_offset=k_off,
                        extra_vary_axes=(
                            (self.model_axis,) if self.model_axis else ()
                        ),
                    )
                else:
                    out = local_attention(
                        q, ck.value, cv.value, causal=True, q_offset=offset,
                    )
        elif self.seq_axis is None:
            # dense single-device form: dispatch to the best local core
            # (flash kernel on TPU, blockwise off-chip for long T)
            from akka_allreduce_tpu.ops.local_attention import local_attention

            out = local_attention(q, k, v, causal=True)
        elif self.seq_impl == "ring":
            out = ring_attention(q, k, v, self.seq_axis, causal=True)
        elif self.seq_impl == "ulysses":
            out = ulysses_attention(q, k, v, self.seq_axis, causal=True)
        else:
            raise ValueError(f"unknown seq_impl {self.seq_impl!r}")
        y = nn.DenseGeneral(
            d_model,
            axis=(-2, -1),
            dtype=self.compute_dtype,
            name="out",
            use_bias=False,  # partial sum under TP; bias goes after the psum
        )(out)
        if self.model_axis is not None:
            y = lax.psum(y, self.model_axis)
        bias = self.param("out_bias", nn.initializers.zeros, (d_model,))
        return y + bias.astype(y.dtype)


class Block(nn.Module):
    n_heads: int
    n_kv_heads: int | None = None
    mlp_ratio: int = 4
    seq_axis: str | None = None
    seq_impl: str = "ring"
    compute_dtype: jnp.dtype = jnp.float32
    model_axis: str | None = None
    tp_size: int = 1
    decode: bool = False
    max_decode_len: int = 0
    cache_quant: str | None = None

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        hidden = self.mlp_ratio * d_model
        if hidden % self.tp_size:
            raise ValueError(
                f"mlp hidden {hidden} not divisible by {self.tp_size=}"
            )
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        x = x + Attention(
            self.n_heads,
            n_kv_heads=self.n_kv_heads,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            compute_dtype=self.compute_dtype,
            model_axis=self.model_axis,
            tp_size=self.tp_size,
            decode=self.decode,
            max_decode_len=self.max_decode_len,
            cache_quant=self.cache_quant,
        )(h)
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        # TP: hidden dim column-split on the up projection, row-split on the
        # down projection; one psum completes the partial products, and the
        # down bias lands after it (applied once)
        h = nn.Dense(
            hidden // self.tp_size, dtype=self.compute_dtype, name="mlp_up"
        )(h)
        h = nn.gelu(h)
        y = nn.Dense(
            d_model, dtype=self.compute_dtype, name="mlp_down", use_bias=False
        )(h)
        if self.model_axis is not None:
            y = lax.psum(y, self.model_axis)
        bias = self.param("mlp_bias", nn.initializers.zeros, (d_model,))
        return x + y + bias.astype(y.dtype)


class TransformerLM(nn.Module):
    """Tokens (B, T_local) int32 -> logits (B, T_local, vocab) fp32."""

    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_kv_heads: int | None = None  # GQA: fewer K/V heads (1 = MQA)
    n_layers: int = 2
    mlp_ratio: int = 4
    seq_axis: str | None = None
    seq_impl: str = "ring"
    compute_dtype: jnp.dtype = jnp.float32
    model_axis: str | None = None  # tensor-parallel mesh axis (None = no TP)
    tp_size: int = 1  # shards per TP group; kernels declare LOCAL head/hidden
    # rematerialize each block on the backward pass (jax.checkpoint): trades
    # one extra forward of FLOPs for O(layers) activation memory — the knob
    # that lets long sequences fit in HBM
    remat: bool = False
    decode: bool = False  # KV-cache autoregressive mode (models/generate.py)
    max_decode_len: int = 0
    cache_quant: str | None = None  # "int8" = quantized KV cache

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab, self.d_model, dtype=self.compute_dtype)(tokens)
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.n_layers):
            # explicit names: nn.remat would otherwise rename the scope to
            # CheckpointBlock_i, forking the param tree from the non-remat
            # (and init-twin) layout — remat must change memory, not params
            x = block_cls(
                self.n_heads,
                n_kv_heads=self.n_kv_heads,
                mlp_ratio=self.mlp_ratio,
                seq_axis=self.seq_axis,
                seq_impl=self.seq_impl,
                compute_dtype=self.compute_dtype,
                model_axis=self.model_axis,
                tp_size=self.tp_size,
                decode=self.decode,
                max_decode_len=self.max_decode_len,
                cache_quant=self.cache_quant,
                name=f"Block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        logits = nn.Dense(self.vocab, dtype=self.compute_dtype)(x)
        return logits.astype(jnp.float32)


class MoEBlock(nn.Module):
    """Transformer block whose MLP is a Switch-routed mixture of experts.

    With ``expert_axis``/``ep_size`` set, each device owns
    ``n_experts / ep_size`` experts (the w1/b1/w2 leading dims are the
    sharded dims — see :func:`ep_param_specs`) and tokens reach their expert
    through the all_to_all pair in ``ops.moe``. The router is replicated:
    every device routes its own tokens over the FULL expert set.
    Returns ``(x, aux, dropped)`` — the Switch load-balancing loss and the
    fraction of tokens dropped past capacity ride alongside (the drop
    fraction is the signal for tuning ``capacity_factor``).
    """

    n_heads: int
    n_kv_heads: int | None = None
    n_experts: int = 4
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.float32
    expert_axis: str | None = None
    ep_size: int = 1
    router_topk: int = 1  # 1 = Switch, 2 = GShard top-2
    seq_axis: str | None = None  # sequence-parallel axis (ring/Ulysses attn)
    seq_impl: str = "ring"
    dispatch_impl: str = "auto"  # "einsum" | "scatter" | "auto" (ops.moe)

    @nn.compact
    def __call__(self, x):
        from akka_allreduce_tpu.ops.moe import moe_dispatch_compute

        d_model = x.shape[-1]
        hidden = self.mlp_ratio * d_model
        if self.n_experts % self.ep_size:
            raise ValueError(
                f"{self.n_experts=} not divisible by {self.ep_size=}"
            )
        e_local = self.n_experts // self.ep_size
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        x = x + Attention(
            self.n_heads,
            n_kv_heads=self.n_kv_heads,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            compute_dtype=self.compute_dtype,
        )(h)
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        router = self.param(
            "router", nn.initializers.lecun_normal(), (d_model, self.n_experts)
        )
        w1 = self.param(
            "moe_w1", nn.initializers.lecun_normal(), (e_local, d_model, hidden)
        )
        b1 = self.param("moe_b1", nn.initializers.zeros, (e_local, hidden))
        w2 = self.param(
            "moe_w2", nn.initializers.lecun_normal(), (e_local, hidden, d_model)
        )
        flat = h.reshape(-1, d_model)
        y, aux, dropped = moe_dispatch_compute(
            flat,
            router,
            w1,
            b1,
            w2,
            n_experts=self.n_experts,
            capacity_factor=self.capacity_factor,
            expert_axis=self.expert_axis if self.ep_size > 1 else None,
            router_topk=self.router_topk,
            seq_axis=self.seq_axis,
            dispatch_impl=self.dispatch_impl,
        )
        return x + y.reshape(x.shape), aux, dropped


class MoETransformerLM(nn.Module):
    """Decoder-only LM with Switch-MoE MLPs:
    tokens -> (logits, aux_loss, dropped_fraction)."""

    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_kv_heads: int | None = None
    n_layers: int = 2
    n_experts: int = 4
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.float32
    expert_axis: str | None = None
    ep_size: int = 1
    router_topk: int = 1  # 1 = Switch, 2 = GShard top-2
    seq_axis: str | None = None  # sequence-parallel axis (ring/Ulysses attn)
    seq_impl: str = "ring"
    dispatch_impl: str = "auto"  # "einsum" | "scatter" | "auto" (ops.moe)

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab, self.d_model, dtype=self.compute_dtype)(tokens)
        aux_total = jnp.float32(0.0)
        dropped_total = jnp.float32(0.0)
        for _ in range(self.n_layers):
            x, aux, dropped = MoEBlock(
                self.n_heads,
                n_kv_heads=self.n_kv_heads,
                n_experts=self.n_experts,
                mlp_ratio=self.mlp_ratio,
                capacity_factor=self.capacity_factor,
                compute_dtype=self.compute_dtype,
                expert_axis=self.expert_axis,
                ep_size=self.ep_size,
                router_topk=self.router_topk,
                seq_axis=self.seq_axis,
                seq_impl=self.seq_impl,
                dispatch_impl=self.dispatch_impl,
            )(x)
            aux_total = aux_total + aux
            dropped_total = dropped_total + dropped
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        logits = nn.Dense(self.vocab, dtype=self.compute_dtype)(x)
        return (
            logits.astype(jnp.float32),
            aux_total / self.n_layers,
            dropped_total / self.n_layers,
        )


def ep_param_specs(tree, expert_axis: str):
    """PartitionSpec pytree for expert parallelism: the moe_w1/b1/w2 leaves
    shard their leading (expert) dim over ``expert_axis``; the router and
    everything else replicate. Same path-rule mechanism as
    :func:`tp_param_specs`, so it also shards optax moment trees."""
    import jax

    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        joined = "/".join(str(n) for n in names)
        if joined.endswith("moe_w1") or joined.endswith("moe_w2"):
            return P(expert_axis, None, None)
        if joined.endswith("moe_b1"):
            return P(expert_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def tp_param_specs(tree, model_axis: str):
    """PartitionSpec pytree for Megatron-style TP over ``model_axis``.

    Matches the layout the modules above declare: q/k/v kernels and biases
    shard on the HEAD dim, the out-projection kernel on its head input dim,
    the MLP up projection on the hidden (output) dim and the down projection
    on the hidden (input) dim. Everything else — embeddings, norms, the
    post-psum biases, the LM head — replicates. Apply to FULL-shape params
    (``tp_size=1`` geometry); ``shard_map`` in_specs then deliver each shard
    its local slice, matching the ``tp_size>1`` module's declared shapes.

    Works on any tree whose leaf PATHS embed the param names — the params
    themselves, or an optax state (adam's mu/nu mirror the param tree, so
    the same path rules shard the optimizer moments identically; scalars
    like adam's step count match no rule and replicate).
    """
    import jax

    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        joined = "/".join(str(n) for n in names)
        if "/q/" in joined or "/k/" in joined or "/v/" in joined:
            if joined.endswith("kernel"):
                return P(None, model_axis, None)
            return P(model_axis, None)  # bias (heads, head_dim)
        if joined.endswith("out/kernel"):
            return P(model_axis, None, None)
        if joined.endswith("mlp_up/kernel"):
            return P(None, model_axis)
        if joined.endswith("mlp_up/bias"):
            return P(model_axis)
        if joined.endswith("mlp_down/kernel"):
            return P(model_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)
