"""MLP for the MNIST data-parallel SGD workload (BASELINE.json:9,
SURVEY.md §4.4). Matmul-shaped for the MXU: wide dense layers, bf16 compute
with fp32 params when ``compute_dtype`` says so."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """784 -> hidden... -> classes, ReLU, optional bf16 compute."""

    hidden: Sequence[int] = (512, 512)
    classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.compute_dtype)
        for width in self.hidden:
            x = nn.Dense(width, dtype=self.compute_dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)  # logits in fp32 for a stable softmax
