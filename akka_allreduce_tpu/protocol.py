"""Round wire protocol.

The reference's actor message protocol (SURVEY.md §3 "Message protocol":
``StartAllreduce``, ``ScatterBlock``, ``ReduceBlock``, ``CompleteAllreduce``,
``PrepareAllreduce``, ``ConfirmPreparation``), kept message-for-message so the
control plane can be unit-tested exactly the way the reference's is (SURVEY.md §5:
hand-deliver messages to one real worker wired to fake peers, assert emitted
messages).

On TPU these messages carry *control* information only. In the host (engine) data
path — used for tests, CPU fallback, and DCN-side chunk movement — ``ScatterBlock``
/ ``ReduceBlock`` carry numpy payloads; on the ICI path payloads never appear in
messages at all (they stay in HBM and move inside one fused XLA collective,
BASELINE.json:5).

Messages are frozen dataclasses: picklable (so they can cross process boundaries
over any host transport). Payload-carrying messages (``ScatterBlock``,
``ReduceBlock``, ``AllReduceInput``, ``AllReduceOutput``) use ``eq=False`` —
ndarray fields make generated equality raise — so they compare and hash by
identity; pure-control messages compare by value (handy in tests).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class StartAllreduce:
    """LineMaster -> worker: begin round ``round_num``.

    ``epoch`` is the issuing master's leadership epoch (RESILIENCE.md
    "Tier 4"): after a failover, nodes reject round triggers from a fenced
    zombie leader. ``-1`` = unfenced (in-process systems, tests).
    """

    round_num: int
    epoch: int = -1


@dataclasses.dataclass(frozen=True, eq=False)
class ScatterBlock:
    """Worker -> peer: one chunk of the sender's partition of its input.

    ``value`` is the chunk destined for ``dest_id``'s block, chunk ``chunk_id``.
    """

    value: np.ndarray
    src_id: int
    dest_id: int
    chunk_id: int
    round_num: int

    def __post_init__(self) -> None:
        object.__setattr__(self, "value", np.asarray(self.value, dtype=np.float32))


@dataclasses.dataclass(frozen=True, eq=False)
class ReduceBlock:
    """Worker -> peer: a reduced (summed) chunk plus its contributor count.

    ``count`` is the number of peers whose scatter contribution made it into the
    sum before ``th_reduce`` fired — consumers divide by it to get the partial
    average (threshold semantics, SURVEY.md §3 "Collective semantics").
    """

    value: np.ndarray
    src_id: int
    dest_id: int
    chunk_id: int
    round_num: int
    count: int

    def __post_init__(self) -> None:
        object.__setattr__(self, "value", np.asarray(self.value, dtype=np.float32))


@dataclasses.dataclass(frozen=True)
class CompleteAllreduce:
    """Worker -> LineMaster: this worker's round output is flushed."""

    src_id: int
    round_num: int


@dataclasses.dataclass(frozen=True)
class PrepareAllreduce:
    """Master/LineMaster -> worker: (re)configuration handshake.

    Sent on membership change (dropout, late joiner): workers rebuild buffers for
    the new peer list and confirm before rounds resume (SURVEY.md §4.5).
    """

    config_id: int
    peer_ids: Sequence[int]
    worker_id: int
    round_num: int
    # where CompleteAllreduce/ConfirmPreparation go. The reference's workers
    # reply to the sending actor; explicit handlers need the address spelled out.
    line_id: int = 0
    # issuing master's leadership epoch (-1 = unfenced); a node that has
    # joined a newer master drops configuration attempts from the old one
    epoch: int = -1

    def __post_init__(self) -> None:
        object.__setattr__(self, "peer_ids", tuple(self.peer_ids))


@dataclasses.dataclass(frozen=True)
class ConfirmPreparation:
    """Worker -> master: buffers rebuilt for ``config_id``; ready to resume."""

    config_id: int
    worker_id: int


# --- dataSource / dataSink seam (SURVEY.md §3 "Data source/sink API") ---------


@dataclasses.dataclass(frozen=True)
class AllReduceInputRequest:
    """Engine -> dataSource: pull the payload for ``iteration``."""

    iteration: int


@dataclasses.dataclass(frozen=True, eq=False)
class AllReduceInput:
    """dataSource -> engine: the flat float payload for one round."""

    data: np.ndarray

    def __post_init__(self) -> None:
        object.__setattr__(self, "data", np.asarray(self.data, dtype=np.float32))


@dataclasses.dataclass(frozen=True, eq=False)
class AllReduceOutput:
    """Engine -> dataSink: reduced sums plus per-element contributor counts.

    The consumer divides ``data`` by ``count`` (elementwise, guarding zeros) to
    obtain the partial average — the reference's ``ReduceBlock.count``
    normalization generalized to the whole buffer.
    """

    data: np.ndarray
    count: np.ndarray
    iteration: int

    def __post_init__(self) -> None:
        object.__setattr__(self, "data", np.asarray(self.data, dtype=np.float32))
        object.__setattr__(self, "count", np.asarray(self.count, dtype=np.int32))

    def average(self) -> np.ndarray:
        """Sum / count with zero-contribution elements left at 0.

        One implementation point for the consumer divide: the native engine's
        fused kernel when built, numpy otherwise (both return exact 0 where
        count == 0 — unfilled chunks hold zero sums anyway).
        """
        from akka_allreduce_tpu import native

        return native.average(self.data, self.count)
