"""Round wire protocol.

The reference's actor message protocol (SURVEY.md §3 "Message protocol":
``StartAllreduce``, ``ScatterBlock``, ``ReduceBlock``, ``CompleteAllreduce``,
``PrepareAllreduce``, ``ConfirmPreparation``), kept message-for-message so the
control plane can be unit-tested exactly the way the reference's is (SURVEY.md §5:
hand-deliver messages to one real worker wired to fake peers, assert emitted
messages).

On TPU these messages carry *control* information only. In the host (engine) data
path — used for tests, CPU fallback, and DCN-side chunk movement — ``ScatterBlock``
/ ``ReduceBlock`` carry numpy payloads; on the ICI path payloads never appear in
messages at all (they stay in HBM and move inside one fused XLA collective,
BASELINE.json:5).

Messages are frozen dataclasses: picklable (so they can cross process boundaries
over any host transport). Payload-carrying messages (``ScatterBlock``,
``ReduceBlock``, ``AllReduceInput``, ``AllReduceOutput``) use ``eq=False`` —
ndarray fields make generated equality raise — so they compare and hash by
identity; pure-control messages compare by value (handy in tests).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class RoundPolicy:
    """Per-round degradation knobs the leader's :class:`AdaptiveController`
    (control/adapt.py, RESILIENCE.md "Tier 5") stamps onto every
    ``PrepareAllreduce``/``StartAllreduce``, so EVERY worker applies the
    same effective threshold and wire precision for a given round id.

    Falsy fields mean "inherit the configured value" — the default policy
    is a no-op, so systems that never run the controller behave exactly as
    before. On the wire the policy rides as a trailing field with the same
    version-skew contract as the trace trailer: old decoders ignore it,
    and this decoder treats its absence as the default policy.

    - ``th_reduce``: effective scatter-reduce threshold for the round
      (``0.0`` = the configured ``ThresholdConfig.th_reduce``). The
      controller only ever lowers it, bounded by a configured floor.
    - ``wire``: wire precision for the round's payload frames — ``"f32"``,
      ``"f16"`` or ``"int8"`` (``""`` = the configured
      ``MetaDataConfig.wire_dtype``). ``int8`` quantizes with a shared
      per-chunk scale and the send side feeds the quantization residual
      into the next round's chunk (the EF identity,
      ``comm/allreduce.py ring_ef_residual``).
    """

    th_reduce: float = 0.0
    wire: str = ""

    #: wire-mode byte values (``0`` = inherit); keep in sync with
    #: ``control/wire.py``'s trailing-field codec
    WIRE_MODES = ("", "f32", "f16", "int8")

    def __post_init__(self) -> None:
        if self.th_reduce and not (0.0 < self.th_reduce <= 1.0):
            raise ValueError(
                f"policy th_reduce must be 0 or in (0, 1], got {self.th_reduce}"
            )
        if self.wire not in self.WIRE_MODES:
            raise ValueError(
                f"policy wire must be one of {self.WIRE_MODES}, got {self.wire!r}"
            )

    @property
    def is_default(self) -> bool:
        return not self.th_reduce and not self.wire

    def reduce_count(self, peer_size: int) -> int | None:
        """Effective scatter-reduce trigger, or None to keep the
        configured one."""
        if not self.th_reduce:
            return None
        return max(1, math.ceil(self.th_reduce * peer_size))

    def describe(self) -> str:
        """Compact human/JSONL form (span attributes, drill logs)."""
        return f"{self.wire or 'full'}@{self.th_reduce or 'cfg'}"


#: the inherit-everything policy (one shared frozen instance)
DEFAULT_POLICY = RoundPolicy()


@dataclasses.dataclass(frozen=True)
class StartAllreduce:
    """LineMaster -> worker: begin round ``round_num``.

    ``epoch`` is the issuing master's leadership epoch (RESILIENCE.md
    "Tier 4"): after a failover, nodes reject round triggers from a fenced
    zombie leader. ``-1`` = unfenced (in-process systems, tests).
    ``policy`` is the round's :class:`RoundPolicy` — every worker applies
    the SAME effective threshold/precision for this round id, and a
    re-issued Start (``LineMaster.restart_stalled``) carries the round's
    ORIGINAL policy, never the controller's current one.
    """

    round_num: int
    epoch: int = -1
    policy: RoundPolicy = DEFAULT_POLICY


@dataclasses.dataclass(frozen=True, eq=False)
class ScatterBlock:
    """Worker -> peer: one chunk of the sender's partition of its input.

    ``value`` is the chunk destined for ``dest_id``'s block, chunk ``chunk_id``.
    """

    value: np.ndarray
    src_id: int
    dest_id: int
    chunk_id: int
    round_num: int

    def __post_init__(self) -> None:
        object.__setattr__(self, "value", np.asarray(self.value, dtype=np.float32))


@dataclasses.dataclass(frozen=True, eq=False)
class ReduceBlock:
    """Worker -> peer: a reduced (summed) chunk plus its contributor count.

    ``count`` is the number of peers whose scatter contribution made it into the
    sum before ``th_reduce`` fired — consumers divide by it to get the partial
    average (threshold semantics, SURVEY.md §3 "Collective semantics").
    """

    value: np.ndarray
    src_id: int
    dest_id: int
    chunk_id: int
    round_num: int
    count: int

    def __post_init__(self) -> None:
        object.__setattr__(self, "value", np.asarray(self.value, dtype=np.float32))


@dataclasses.dataclass(frozen=True)
class CompleteAllreduce:
    """Worker -> LineMaster: this worker's round output is flushed."""

    src_id: int
    round_num: int


@dataclasses.dataclass(frozen=True)
class PrepareAllreduce:
    """Master/LineMaster -> worker: (re)configuration handshake.

    Sent on membership change (dropout, late joiner): workers rebuild buffers for
    the new peer list and confirm before rounds resume (SURVEY.md §4.5).
    """

    config_id: int
    peer_ids: Sequence[int]
    worker_id: int
    round_num: int
    # where CompleteAllreduce/ConfirmPreparation go. The reference's workers
    # reply to the sending actor; explicit handlers need the address spelled out.
    line_id: int = 0
    # issuing master's leadership epoch (-1 = unfenced); a node that has
    # joined a newer master drops configuration attempts from the old one
    epoch: int = -1
    # the RoundPolicy in force when this configuration was prepared (the
    # controller's current level) — re-sent Prepares carry the SAME one
    policy: RoundPolicy = DEFAULT_POLICY

    def __post_init__(self) -> None:
        object.__setattr__(self, "peer_ids", tuple(self.peer_ids))


@dataclasses.dataclass(frozen=True)
class ConfirmPreparation:
    """Worker -> master: buffers rebuilt for ``config_id``; ready to resume."""

    config_id: int
    worker_id: int


# --- dataSource / dataSink seam (SURVEY.md §3 "Data source/sink API") ---------


@dataclasses.dataclass(frozen=True)
class AllReduceInputRequest:
    """Engine -> dataSource: pull the payload for ``iteration``."""

    iteration: int


@dataclasses.dataclass(frozen=True, eq=False)
class AllReduceInput:
    """dataSource -> engine: the flat float payload for one round."""

    data: np.ndarray

    def __post_init__(self) -> None:
        object.__setattr__(self, "data", np.asarray(self.data, dtype=np.float32))


@dataclasses.dataclass(frozen=True, eq=False)
class AllReduceOutput:
    """Engine -> dataSink: reduced sums plus per-element contributor counts.

    The consumer divides ``data`` by ``count`` (elementwise, guarding zeros) to
    obtain the partial average — the reference's ``ReduceBlock.count``
    normalization generalized to the whole buffer.
    """

    data: np.ndarray
    count: np.ndarray
    iteration: int

    def __post_init__(self) -> None:
        object.__setattr__(self, "data", np.asarray(self.data, dtype=np.float32))
        object.__setattr__(self, "count", np.asarray(self.count, dtype=np.int32))

    def average(self) -> np.ndarray:
        """Sum / count with zero-contribution elements left at 0.

        One implementation point for the consumer divide: the native engine's
        fused kernel when built, numpy otherwise (both return exact 0 where
        count == 0 — unfilled chunks hold zero sums anyway).
        """
        from akka_allreduce_tpu import native

        return native.average(self.data, self.count)
