"""MFU benchmark: slope-timed on-device training chains + a FLOP model.

VERDICT r2 #1: the compute side of the framework gets the same measurement
honesty as the reduce kernel (bench.py). Each workload runs its trainer's
``train_chain`` (zero host I/O inside the loop), times it as the difference
between a short and a long chain dispatch (constant tunnel RTT/dispatch
overhead cancels; both lengths pre-compiled), and reports model-FLOPs
utilization against the chip's dense bf16 peak
(``utils/benchmarking.device_peak_flops``).

Conventions (see utils/benchmarking.py): model FLOPs exclude remat
recompute (with ``--remat`` the printed MFU is the true model-work
fraction, not the hardware-busy fraction), attention counts causal-halved
score/value matmuls, MoE counts ACTIVE params only, ResNet uses the
nominal SAME-padding conv count (XLA skips edge-padding MACs, so tiny
images can overstate utilization by the padding share — <5 % at the
sizes used here).

Flagship config (``--workload lm`` defaults): d_model 2048, 16 heads
(head_dim 128 = one MXU lane tile), 8 layers, seq 2048, batch 8, bf16
compute, flash attention — 404M params, sized so params + adam moments
(f32) + activations fill a 16 GB v5e without remat.
"""

from __future__ import annotations

import argparse
import json
import time


def _chain_mfu_record(
    name: str,
    timed,
    flops_per_step: float,
    *,
    lo: int = 2,
    hi: int = 10,
    outer: int = 4,
    n_devices: int = 1,
    extra: dict | None = None,
) -> dict:
    """Time ``timed(steps)`` chains at two lengths, return the JSON record."""
    import jax

    from akka_allreduce_tpu.utils.benchmarking import (
        device_peak_flops,
        median_slope,
        mfu,
    )

    t0 = time.perf_counter()
    timed(lo)
    timed(hi)  # compile BOTH lengths before any timing pair
    compile_s = time.perf_counter() - t0
    # fast steps need a longer chain: rescale hi so the DIFFERENTIAL
    # (hi - lo) on-device signal reaches ~3 s and tunnel RTT jitter
    # (~0.1 s) stays in the noise — the same discipline as
    # median_slope's target_signal_s, but done here because train_chain's
    # step count is a STATIC scan length (a new hi pays one more
    # compile, folded into compile_s; median_slope's built-in rescale
    # assumes a traced trip count). The probe itself lives in the jittery
    # regime it is sizing against, so take a median of 3 pairs; a
    # non-positive median means the signal is still drowned — escalate
    # by a bounded factor rather than silently keeping the bad hi
    # (median_slope's own escalation rule).
    import statistics

    timing_suspect = False
    for attempt in range(4):  # probe, escalate, re-probe — at most 3 times
        rough = statistics.median(
            (timed(hi) - timed(lo)) / (hi - lo) for _ in range(3)
        )
        if rough > 0 and rough * (hi - lo) >= 2.0:
            break  # differential signal reaches the ~3 s target
        if attempt == 3:
            # escalations exhausted with the probe still noise-dominated —
            # the emitted slope may be unreliable; say so in the record
            timing_suspect = True
            break
        if rough <= 0:
            # same 100k-step ceiling as the measured branch, so a noisy
            # probe can never compound past it (the new_hi <= hi break
            # then fires and flags the record)
            new_hi = lo + min((hi - lo) * 16, 100_000)
        else:
            new_hi = lo + min(int(round(3.0 / rough)), 100_000)
        if new_hi <= hi:
            timing_suspect = True  # capped (100k steps); signal still short
            break
        hi = new_hi
        t1 = time.perf_counter()
        timed(hi)  # compile the rescaled length
        compile_s += time.perf_counter() - t1
    est = median_slope(timed, lo, hi, outer=outer, warmup=False)
    sec = est.seconds_per_iter
    u = mfu(flops_per_step, sec, device_peak_flops(), n_devices=n_devices)
    metric = f"{name}_mfu"
    if est.noisy():
        metric += "_NOISY"
    rec = {
        "metric": metric,
        "value": round(u, 4) if u is not None else None,
        "unit": "mfu",
        "tflops_per_step": round(flops_per_step / 1e12, 3),
        "tflops_per_s": round(flops_per_step / sec / 1e12, 2),
        "ms_per_step": round(sec * 1e3, 2),
        "spread_pct": est.spread_pct,
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
    }
    if timing_suspect:
        rec["timing_suspect"] = True
    rec.update(extra or {})
    return rec


def run_lm(args) -> dict:
    import jax
    import jax.numpy as jnp

    from akka_allreduce_tpu.models.data import SyntheticCopyLM
    from akka_allreduce_tpu.parallel import data_seq_mesh
    from akka_allreduce_tpu.train import LongContextTrainer
    from akka_allreduce_tpu.utils.benchmarking import transformer_train_flops

    heads = args.heads or max(1, args.d_model // 128)
    mesh = data_seq_mesh(args.dp, args.sp)
    trainer = LongContextTrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=heads,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers,
        seq_len=args.seq_len,
        compute_dtype=jnp.bfloat16,
        remat=bool(args.remat),
        learning_rate=1e-3,
    )
    rows = max(1, args.batch // trainer.dp)
    batch = rows * trainer.dp
    sampler = SyntheticCopyLM(args.seq_len, vocab=args.vocab).device_sampler()

    def timed(steps: int) -> float:
        t0 = time.perf_counter()
        trainer.train_chain(sampler, steps, rows)
        jax.block_until_ready(trainer.params)
        return time.perf_counter() - t0

    flops = transformer_train_flops(
        n_params=trainer.param_count,
        batch=batch,
        seq=args.seq_len,
        d_model=args.d_model,
        n_layers=args.layers,
    )
    return _chain_mfu_record(
        "lm",
        timed,
        flops,
        n_devices=trainer.n_devices,
        extra={
            "params_m": round(trainer.param_count / 1e6, 1),
            "d_model": args.d_model,
            "n_layers": args.layers,
            "seq_len": args.seq_len,
            "batch": batch,
            "remat": args.remat,
            "compute_dtype": "bf16",
        },
    )


def run_mlp(args) -> dict:
    import jax
    import numpy as np

    from akka_allreduce_tpu.models import MLP, data
    from akka_allreduce_tpu.parallel import line_mesh
    from akka_allreduce_tpu.train import DPTrainer
    from akka_allreduce_tpu.utils.benchmarking import dense_train_flops

    # MXU-shaped MLP: wide hidden layers so the matmuls are the story
    hidden = tuple(args.hidden)
    trainer = DPTrainer(
        MLP(hidden=hidden, classes=10),
        line_mesh(),
        example_input=np.zeros((1, 28, 28, 1), np.float32),
        learning_rate=0.1,
    )
    per_dev = max(1, args.batch // trainer.n_devices)
    batch = per_dev * trainer.n_devices
    sampler = data.mnist_like().device_sampler()

    def timed(steps: int) -> float:
        t0 = time.perf_counter()
        losses, _ = trainer.train_chain(
            sampler, steps, per_dev, fetch_metrics=False
        )
        jax.device_get(jax.numpy.ravel(losses)[:1])
        return time.perf_counter() - t0

    return _chain_mfu_record(
        "mlp",
        timed,
        dense_train_flops(trainer.param_count, batch),
        lo=20,
        hi=2020,
        n_devices=trainer.n_devices,
        extra={
            "params_m": round(trainer.param_count / 1e6, 3),
            "hidden": list(hidden),
            "batch": batch,
        },
    )


def run_resnet(args) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from akka_allreduce_tpu.models import ResNet50, data
    from akka_allreduce_tpu.models.resnet import resnet_fwd_flops
    from akka_allreduce_tpu.parallel import line_mesh
    from akka_allreduce_tpu.train import DPTrainer

    model = ResNet50(classes=args.classes, compute_dtype=jnp.bfloat16)
    trainer = DPTrainer(
        model,
        line_mesh(),
        example_input=np.zeros(
            (1, args.image_size, args.image_size, 3), np.float32
        ),
        learning_rate=0.1,
    )
    per_dev = max(1, args.batch // trainer.n_devices)
    batch = per_dev * trainer.n_devices
    ds = data.SyntheticClassification(
        (args.image_size, args.image_size, 3), args.classes, seed=0
    )
    sampler = ds.device_sampler()

    def timed(steps: int) -> float:
        t0 = time.perf_counter()
        losses, _ = trainer.train_chain(
            sampler, steps, per_dev, fetch_metrics=False
        )
        jax.device_get(jax.numpy.ravel(losses)[:1])
        return time.perf_counter() - t0

    flops = 3.0 * resnet_fwd_flops(model, args.image_size, batch)
    # sub-ms steps on the real chip: the hi chain must put seconds of
    # on-device signal against the tunnel's ~0.1 s RTT jitter
    return _chain_mfu_record(
        "resnet",
        timed,
        flops,
        lo=20,
        hi=2020,
        n_devices=trainer.n_devices,
        extra={
            "params_m": round(trainer.param_count / 1e6, 1),
            "image_size": args.image_size,
            "batch": batch,
            "compute_dtype": "bf16",
        },
    )


def run_moe(args) -> dict:
    import jax
    import jax.numpy as jnp

    from akka_allreduce_tpu.models import data
    from akka_allreduce_tpu.train import MoETrainer
    from akka_allreduce_tpu.utils.benchmarking import (
        moe_active_params,
        transformer_train_flops,
    )

    heads = args.heads or max(1, args.d_model // 128)
    devs = jax.devices()
    mesh = jax.make_mesh((1,), ("data",), devices=devs[:1]) if len(
        devs
    ) == 1 else jax.make_mesh((len(devs),), ("data",), devices=devs)
    trainer = MoETrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=heads,
        n_layers=args.layers,
        n_experts=args.experts,
        seq_len=args.seq_len,
        router_topk=args.topk,
        capacity_factor=args.capacity_factor,
        learning_rate=1e-3,
        compute_dtype=jnp.bfloat16,
        dispatch_impl=args.dispatch,
        mu_dtype=jnp.bfloat16 if args.mu_bf16 else None,
    )
    rows = max(1, args.batch // trainer.n_devices)
    batch = rows * trainer.n_devices
    sampler = data.lm_copy_task(args.seq_len, vocab=args.vocab).device_sampler()

    def timed(steps: int) -> float:
        t0 = time.perf_counter()
        trainer.train_chain(sampler, steps, rows_per_device=rows)
        jax.block_until_ready(trainer.params)
        return time.perf_counter() - t0

    active = moe_active_params(trainer.params, args.topk, args.experts)
    flops = transformer_train_flops(
        n_params=active,
        batch=batch,
        seq=args.seq_len,
        d_model=args.d_model,
        n_layers=args.layers,
    )
    rec = _chain_mfu_record(
        "moe",
        timed,
        flops,
        n_devices=trainer.n_devices,
        extra={
            "params_m": round(trainer.param_count / 1e6, 1),
            "active_params_m": round(active / 1e6, 1),
            "dispatch": args.dispatch,
            "experts": args.experts,
            "topk": args.topk,
            "mu_bf16": args.mu_bf16,
            "capacity_factor": args.capacity_factor,
            "d_model": args.d_model,
            "n_layers": args.layers,
            "seq_len": args.seq_len,
            "batch": batch,
            "compute_dtype": "bf16",
        },
    )
    # the capacity trade must ride the record: tighter capacity_factor
    # trims empty-slot FFN compute but drops more assignments. Sampled
    # AFTER the timing with the lo=2 chain length so the (2, rows) cache
    # entry from the timed runs is reused — no extra compile
    drop_sample = trainer.train_chain(sampler, 2, rows_per_device=rows)
    rec["dropped_frac"] = round(
        float(sum(m.dropped for m in drop_sample) / len(drop_sample)), 4
    )
    return rec


def run_fsdp(args) -> dict:
    import jax
    import jax.numpy as jnp

    from akka_allreduce_tpu.models.data import SyntheticCopyLM
    from akka_allreduce_tpu.parallel import data_seq_mesh, line_mesh
    from akka_allreduce_tpu.train import FSDPLMTrainer
    from akka_allreduce_tpu.utils.benchmarking import transformer_train_flops

    heads = args.heads or max(1, args.d_model // 128)
    # honor the mesh flags the lm workload honors (FSDP x SP; a flat
    # line mesh otherwise)
    if (args.sp or 1) > 1:
        mesh = data_seq_mesh(args.dp, args.sp)
    elif args.dp:
        mesh = line_mesh(args.dp)
    else:
        mesh = line_mesh()
    trainer = FSDPLMTrainer(
        mesh,
        vocab=args.vocab,
        d_model=args.d_model,
        n_heads=heads,
        n_kv_heads=args.kv_heads,
        n_layers=args.layers,
        seq_len=args.seq_len,
        compute_dtype=jnp.bfloat16,
        remat=args.remat,
        prefetch=args.prefetch,
        learning_rate=1e-3,
    )
    rows = max(1, args.batch // trainer.dp)
    batch = rows * trainer.dp
    sampler = SyntheticCopyLM(args.seq_len, vocab=args.vocab).device_sampler()

    def timed(steps: int) -> float:
        t0 = time.perf_counter()
        trainer.train_chain(sampler, steps, rows)
        jax.block_until_ready(trainer.params)
        return time.perf_counter() - t0

    flops = transformer_train_flops(
        n_params=trainer.param_count,
        batch=batch,
        seq=args.seq_len,
        d_model=args.d_model,
        n_layers=args.layers,
    )
    return _chain_mfu_record(
        "fsdp",
        timed,
        flops,
        n_devices=trainer.n_devices,
        extra={
            "params_m": round(trainer.param_count / 1e6, 1),
            "d_model": args.d_model,
            "n_layers": args.layers,
            "seq_len": args.seq_len,
            "batch": batch,
            "remat": args.remat,
            "prefetch": args.prefetch,
            "compute_dtype": "bf16",
        },
    )


WORKLOADS = {
    "lm": run_lm,
    "mlp": run_mlp,
    "resnet": run_resnet,
    "moe": run_moe,
    "fsdp": run_fsdp,
}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "bench-mfu",
        description="slope-timed on-device MFU for the training workloads "
        "(one JSON line; flagship = lm)",
    )
    p.add_argument("--workload", choices=sorted(WORKLOADS), default="lm")
    p.add_argument("--batch", type=int, default=8, help="global batch size")
    p.add_argument("--d-model", type=int, default=2048)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--heads", type=int, default=None, help="default d/128")
    p.add_argument(
        "--kv-heads", type=int, default=None,
        help="grouped-query attention K/V heads (lm/fsdp workloads)",
    )
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dp", type=int, default=None)
    p.add_argument("--sp", type=int, default=None)
    p.add_argument(
        "--remat",
        nargs="?",
        const="full",
        default=False,
        choices=("full", "params"),
        help="'full' = recompute layers on backward; 'params' (FSDP only) "
        "= re-gather params on backward, keep activations",
    )
    p.add_argument(
        "--prefetch",
        action="store_true",
        help="FSDP only: software-pipeline the param gathers (with "
        "--remat params the trunk unrolls so backward re-gathers overlap "
        "too)",
    )
    p.add_argument("--hidden", type=int, nargs="+", default=[2048, 2048])
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument(
        "--capacity-factor", type=float, default=None,
        help="moe only: expert slot slack (E*C = cf*topk*T). 1.0 removes "
        "the 25%% of expert FFN compute the default spends on empty "
        "slots, at the cost of more dropped assignments (recorded)",
    )
    p.add_argument(
        "--mu-bf16",
        action="store_true",
        help="moe only: adam first moment in bf16 — halves the biggest "
        "traffic stream of the all-expert optimizer update",
    )
    p.add_argument("--topk", type=int, choices=(1, 2), default=1)
    p.add_argument(
        "--dispatch", choices=("auto", "einsum", "scatter"), default="auto"
    )
    args = p.parse_args(argv)
    if args.remat == "params" and args.workload != "fsdp":
        p.error("--remat params is FSDP's regather mode; use --remat full")
    if args.prefetch and args.workload != "fsdp":
        p.error("--prefetch is FSDP's gather pipeline; fsdp workload only")
    if args.mu_bf16 and args.workload != "moe":
        p.error("--mu-bf16 is the MoE optimizer knob; moe workload only")
    if args.capacity_factor is not None and args.workload != "moe":
        p.error("--capacity-factor is the MoE slot knob; moe workload only")
    if args.capacity_factor is None:
        args.capacity_factor = 1.25  # MoETrainer's default
    rec = WORKLOADS[args.workload](args)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
