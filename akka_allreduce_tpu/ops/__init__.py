"""Pallas/XLA kernels for the framework's hot data-plane ops.

Three families:

- :mod:`.local_reduce` — fused single-chip threshold reduce: masked average
  and the elastic-average step over K stacked payloads in ONE pass over HBM
  (XLA needs two: one to form the average, one to apply it).
- :mod:`.ring` — an explicit inter-chip ring allreduce built on Pallas remote
  DMA with double-buffered slots and semaphore back-pressure; the compiled
  equivalent of the reference's chunked ring schedule (SURVEY.md §3
  "ring/chunked schedule", BASELINE.json:9) and the substrate for later
  comm/compute overlap.
- :mod:`.ring_attention` — long-context sequence parallelism: blockwise ring
  attention (K/V rotating over ICI via ppermute, flash-style online softmax)
  and Ulysses all-to-all head/sequence re-sharding. No analog in the
  reference (SURVEY.md §6 — long-context is ABSENT there).

All kernels run in TPU interpret mode on the CPU test backend (including the
interpreter's race detector), so "multi-chip" kernel behavior is tested
without hardware, mirroring the reference's probe-based test philosophy
(SURVEY.md §5).
"""

from akka_allreduce_tpu.ops.local_reduce import (
    elastic_average_step,
    masked_average,
    pack_tiles,
    unpack_tiles,
)
from akka_allreduce_tpu.ops.ring import pallas_ring_allreduce_sum
from akka_allreduce_tpu.ops.local_attention import (
    blockwise_attention,
    local_attention,
)
from akka_allreduce_tpu.ops.ring_attention import (
    attention_reference,
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "attention_reference",
    "blockwise_attention",
    "local_attention",
    "elastic_average_step",
    "masked_average",
    "pack_tiles",
    "unpack_tiles",
    "pallas_ring_allreduce_sum",
    "ring_attention",
    "ulysses_attention",
]
