"""Explicit ring allreduce as a Pallas remote-DMA kernel.

The reference's large-buffer path is a *chunked ring schedule*
(BASELINE.json:9 — ResNet-50's 25M-param buffer): each worker passes chunks
around a ring, accumulating as they go. Here that schedule is a compiled TPU
kernel: reduce-scatter then all-gather over the ICI ring, double-buffered
remote DMA per step, with explicit semaphore back-pressure so a fast neighbor
can never overwrite a slot that has not been consumed yet (the Pallas
interpreter's race detector verifies this in tests/test_pallas_ring.py).

Payloads are processed in VMEM-resident *buckets* — the framework's
``max_chunk_size`` granularity (SURVEY.md §3 "chunked buffers") doubles as
the VMEM staging size, so arbitrarily large buffers stream through a fixed
on-chip footprint.

Call inside ``shard_map``. For the host-facing entry use
``comm.allreduce.build_threshold_allreduce(schedule="pallas_ring")``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
_DEF_SEG_ROWS = 512  # per-step transfer: 512*128 fp32 = 256 KB
_LOGICAL = pltpu.DeviceIdType.LOGICAL


def int8_quantize(seg: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-segment max-abs int8 quantization: ``(q, scale)``.

    THE int8 wire formula — shared by this kernel and the XLA ring
    (comm.allreduce._compress_seg), whose drift-equivalence the tests
    assert; an all-zero segment maps to scale 1 so dequantize never
    divides by zero."""
    amax = jnp.max(jnp.abs(seg))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(seg / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _ring_kernel(n: int, axis_name: str, compress: str | None, x_ref,
                 out_ref, *scratch):
    """One bucket: (n*seg_rows, LANE) in VMEM -> allreduced same shape.

    Unified reduce-scatter + all-gather loop, 2(n-1) steps. Step s:
      RS (s < n-1):   send seg (my-s) % n, accumulate into seg (my-s-1) % n
      AG (s >= n-1):  send seg (my+1-s') % n, copy into seg (my-s') % n
                      with s' = s - (n-1)
    Back-pressure: two recv slots; before reusing a slot (s >= 2) wait until
    the right neighbor consumed what we wrote there two steps ago; after
    consuming a slot, signal the left neighbor. Signals are emitted only for
    steps that have a matching wait (s <= S-3), so every semaphore drains to
    zero by kernel end.

    ``compress``: every hop's wire payload rides bfloat16 (half the ICI
    bytes) or int8 with a per-segment max-abs scale (a quarter; the scale
    travels as a tiny second DMA), staged through ``send_buf``; the VMEM
    accumulator stays f32. Semantics mirror
    comm.allreduce.ring_allreduce_sum(compress=...): partial sums
    re-quantize per RS hop, and the reduced segment is quantized ONCE more
    before the gather phase — on the owner's copy too — so every device
    returns bit-identical output under bf16 (re-casting a bf16-representable
    value is lossless) and ulp-identical under int8 (each AG hop's
    scale = (127·scale)/127 round trip drifts the last f32 bit; the XLA
    int8 ring drifts identically).
    """
    scale_send = scale_recv = scale_send_sem = scale_recv_sem = None
    if compress == "int8":
        (recv_buf, send_buf, scale_recv, scale_send, send_sem, recv_sem,
         scale_send_sem, scale_recv_sem, cap_sem) = scratch
    elif compress == "bf16":
        recv_buf, send_buf, send_sem, recv_sem, cap_sem = scratch
    else:
        (recv_buf, send_sem, recv_sem, cap_sem), send_buf = scratch, None
    seg_rows = x_ref.shape[0] // n
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my - 1 + n, n)

    # Neighbor barrier: nobody starts DMAing until both neighbors are in the
    # kernel (their buffers exist and their semaphores are live).
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=_LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=_LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    out_ref[:] = x_ref[:]
    total_steps = 2 * (n - 1)

    def step(s, _):
        sp = s - (n - 1)  # all-gather step index (valid when s >= n-1)
        rs = s < n - 1
        send_idx = lax.rem(jnp.where(rs, my - s, my + 1 - sp) + 2 * n, n)
        recv_idx = lax.rem(jnp.where(rs, my - s - 1, my - sp) + 2 * n, n)
        slot = lax.rem(s, 2)

        quantize = int8_quantize

        if compress is not None:
            # entering the gather phase: quantize the OWNED reduced segment
            # (seg (my+1) % n, the first AG send) in place, so the owner's
            # copy equals what every peer will reconstruct from the wire
            @pl.when(s == n - 1)
            def _():
                own = pl.ds(lax.rem(my + 1, n) * seg_rows, seg_rows)
                if compress == "bf16":
                    out_ref[own] = (
                        out_ref[own].astype(jnp.bfloat16).astype(out_ref.dtype)
                    )
                else:
                    q, scale = quantize(out_ref[own])
                    out_ref[own] = q.astype(out_ref.dtype) * scale

        @pl.when(s >= 2)
        def _():
            pltpu.semaphore_wait(cap_sem, 1)

        src_slice = pl.ds(send_idx * seg_rows, seg_rows)
        if compress is not None:
            # stage the hop payload compressed: the DMA then moves half
            # (bf16) or a quarter (int8) of the bytes; the previous send
            # from this slot completed at step s-2 (rdma.wait() blocks on
            # send completion), so the write is safe
            if compress == "bf16":
                send_buf[slot] = out_ref[src_slice].astype(send_buf.dtype)
            else:
                q, scale = quantize(out_ref[src_slice])
                send_buf[slot] = q
                scale_send[slot] = jnp.full((1, LANE), scale, jnp.float32)
            src_ref = send_buf.at[slot]
        else:
            src_ref = out_ref.at[src_slice]
        rdma = pltpu.make_async_remote_copy(
            src_ref=src_ref,
            dst_ref=recv_buf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=_LOGICAL,
        )
        rdma.start()
        if compress == "int8":
            scale_rdma = pltpu.make_async_remote_copy(
                src_ref=scale_send.at[slot],
                dst_ref=scale_recv.at[slot],
                send_sem=scale_send_sem.at[slot],
                recv_sem=scale_recv_sem.at[slot],
                device_id=right,
                device_id_type=_LOGICAL,
            )
            scale_rdma.start()
            scale_rdma.wait()
        # wait() blocks on BOTH our send completing and the symmetric
        # incoming copy from the left neighbor landing in recv_buf[slot]
        rdma.wait()

        dst = pl.ds(recv_idx * seg_rows, seg_rows)
        if compress == "int8":
            recv_val = (
                recv_buf[slot].astype(out_ref.dtype)
                * scale_recv[slot][0, 0]
            )
        else:
            recv_val = recv_buf[slot].astype(out_ref.dtype)

        @pl.when(rs)
        def _():
            out_ref[dst] = out_ref[dst] + recv_val

        @pl.when(jnp.logical_not(rs))
        def _():
            out_ref[dst] = recv_val

        # slot consumed: left neighbor may overwrite it (their step s+2)
        @pl.when(s <= total_steps - 3)
        def _():
            pltpu.semaphore_signal(cap_sem, inc=1, device_id=left,
                                   device_id_type=_LOGICAL)
        return 0

    lax.fori_loop(0, total_steps, step, 0)


def pallas_ring_allreduce_sum(
    x: jax.Array,
    axis_name: str,
    axis_size: int,
    *,
    seg_rows: int = _DEF_SEG_ROWS,
    interpret: bool | None = None,
    detect_races: bool = False,
    compress: str | None = None,
    collective_id: int = 7,
) -> jax.Array:
    """Ring-allreduce ``sum(x)`` over ``axis_name`` inside ``shard_map``.

    ``x`` is this device's flat ``(data,)`` payload. Data is padded to whole
    buckets of ``axis_size * seg_rows * LANE`` elements; buckets stream
    sequentially through one VMEM-resident kernel launch each.

    ``interpret`` defaults to True off-TPU (the Pallas TPU interpreter), so
    the same kernel is testable on the CPU mesh; ``detect_races=True`` turns
    on the interpreter's race detector (tests only — it is slow).

    Callers that know their mesh (comm.allreduce) pass ``interpret``
    explicitly from the mesh's device platform: ``jax.default_backend()`` is
    the wrong signal when a TPU plugin is present but the mesh is a virtual
    CPU one — compiled-mode Pallas would then lower onto CPU and fail.

    ``compress`` stages every hop through a compressed send buffer —
    ``"bf16"`` halves the wire bytes, ``"int8"`` quarters them with a
    per-segment max-abs scale riding a tiny second DMA; f32 VMEM
    accumulation either way (see ``_ring_kernel``).
    ``collective_id`` must be UNIQUE among collective Pallas kernels alive
    in one program; compose-with-another-kernel callers pass their own.
    """
    n = axis_size
    if n == 1:
        return x
    if compress not in (None, "bf16", "int8"):
        raise ValueError(f"unknown compress mode {compress!r}")
    if interpret is None:
        from akka_allreduce_tpu.ops._platform import interpret_default

        interpret = interpret_default(x)
    data = x.shape[0]
    bucket = n * seg_rows * LANE
    n_buckets = max(1, -(-data // bucket))
    x = jnp.pad(x, (0, n_buckets * bucket - data))
    xb = x.reshape(n_buckets, n * seg_rows, LANE)

    if interpret:
        interp = pltpu.InterpretParams(detect_races=detect_races)
    else:
        interp = False

    wire = {"bf16": jnp.bfloat16, "int8": jnp.int8, None: x.dtype}[compress]
    scratch = [pltpu.VMEM((2, seg_rows, LANE), wire)]  # recv slots
    if compress is not None:
        scratch.append(pltpu.VMEM((2, seg_rows, LANE), wire))  # send staging
    if compress == "int8":
        # per-segment scales: one f32 each, padded to a lane tile
        scratch.append(pltpu.VMEM((2, 1, LANE), jnp.float32))  # scale recv
        scratch.append(pltpu.VMEM((2, 1, LANE), jnp.float32))  # scale send
    scratch += [
        pltpu.SemaphoreType.DMA((2,)),  # send
        pltpu.SemaphoreType.DMA((2,)),  # recv
    ]
    if compress == "int8":
        scratch += [
            pltpu.SemaphoreType.DMA((2,)),  # scale send
            pltpu.SemaphoreType.DMA((2,)),  # scale recv
        ]
    scratch.append(pltpu.SemaphoreType.REGULAR)  # capacity (back-pressure)
    call = pl.pallas_call(
        functools.partial(_ring_kernel, n, axis_name, compress),
        out_shape=jax.ShapeDtypeStruct((n * seg_rows, LANE), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )

    def one_bucket(carry, xi):
        return carry, call(xi)

    _, out = lax.scan(one_bucket, 0, xb)
    return out.reshape(-1)[:data]
