"""Mixture-of-experts dispatch: Switch-style top-1 routing + expert-parallel
all-to-all.

The reference has no model parallelism of any kind (SURVEY.md §3 — DP is its
entire point); MoE/EP is a beyond-parity capability of the TPU rebuild, built
the TPU way:

- **static shapes**: routing uses a fixed per-(device, expert) capacity
  ``C = ceil(T_local * capacity_factor / n_experts)``; overflow tokens are
  dropped (their residual path passes through untouched) — the Switch
  Transformer discipline, which keeps every einsum MXU-shaped and lets XLA
  compile one program regardless of routing decisions;
- **dispatch is matmul**: tokens move into expert slots via one-hot
  einsums, not gathers — exactly what the MXU is good at;
- **EP = all_to_all over a mesh axis**: with experts sharded over
  ``expert_axis`` (ep devices x E/ep experts each), one ``lax.all_to_all``
  carries every device's per-expert slot block to the expert's owner and a
  second one brings outputs back — the standard a2a pair riding ICI.

All functions are pure and shard_map-compatible; the dense (no-EP) path is
the oracle the EP path is tested against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class RouteResult(NamedTuple):
    dispatch: jax.Array  # (T, E, C) one-hot token->slot assignment
    combine: jax.Array  # (T, E, C) dispatch scaled by the router gate
    aux_loss: jax.Array  # scalar Switch load-balancing loss
    dropped: jax.Array  # scalar fraction of tokens past capacity


def switch_route(
    logits: jax.Array, capacity: int
) -> RouteResult:
    """Top-1 (Switch) routing with static capacity.

    ``logits``: (T, E) router scores for T tokens over E experts.
    ``capacity``: max tokens per expert (this device's contribution).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = probs.max(axis=-1)  # (T,)
    idx = probs.argmax(axis=-1)  # (T,)
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's queue (0-based)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (T, E)
    pos_t = pos.sum(axis=-1)  # (T,)
    keep = (pos_t < capacity).astype(jnp.float32)
    slot = jnp.minimum(pos_t, capacity - 1).astype(jnp.int32)
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(slot, capacity)[:, None, :]
        * keep[:, None, None]
    )  # (T, E, C)
    combine = dispatch * gate[:, None, None]
    # Switch aux loss: E * sum_e f_e * P_e  (f = fraction routed, P = mean prob)
    f = onehot.mean(axis=0)
    p = probs.mean(axis=0)
    aux = e * jnp.sum(f * p)
    dropped = 1.0 - keep.mean()
    return RouteResult(dispatch, combine, aux, dropped)


def expert_ffn(xs: jax.Array, w1, b1, w2) -> jax.Array:
    """Batched per-expert 2-layer MLP: (E_local, N, d) -> (E_local, N, d)."""
    h = jnp.einsum("end,edh->enh", xs, w1) + b1[:, None, :]
    h = jax.nn.gelu(h)
    return jnp.einsum("enh,ehd->end", h, w2)


def moe_dispatch_compute(
    x: jax.Array,
    router_w: jax.Array,
    w1: jax.Array,
    b1: jax.Array,
    w2: jax.Array,
    *,
    n_experts: int,
    capacity_factor: float = 1.25,
    expert_axis: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Route ``x`` (T, d) through the expert MLPs; returns (y, aux, dropped).

    Expert weights are LOCAL shards: ``w1`` is (E/ep, d, hidden) when
    ``expert_axis`` names an ep-sized mesh axis (run inside shard_map), or the
    full (E, d, hidden) dense form when ``expert_axis`` is None.
    """
    t = x.shape[0]
    capacity = max(1, -(-int(t * capacity_factor) // n_experts))
    # routing numerics (softmax/cumsum) stay float32; the heavy einsums below
    # run in x's dtype so bf16 compute flows through the expert path
    logits = x.astype(jnp.float32) @ router_w  # (T, E) — router always full E
    route = switch_route(logits, capacity)
    w1, b1, w2 = (w.astype(x.dtype) for w in (w1, b1, w2))
    # tokens -> per-expert slots: (E, C, d)
    slots = jnp.einsum("tec,td->ecd", route.dispatch.astype(x.dtype), x)
    if expert_axis is None:
        ys = expert_ffn(slots, w1, b1, w2)  # dense: all experts local
    else:
        ep = lax.psum(1, expert_axis)
        e_local = n_experts // ep
        c = slots.shape[1]
        d = slots.shape[2]
        # (E, C, d) -> exchange so each device holds ITS experts' slots from
        # every peer: tiled a2a splits dim 0 into ep blocks of e_local
        inbound = lax.all_to_all(
            slots, expert_axis, split_axis=0, concat_axis=0, tiled=True
        )  # (ep * e_local, C, d): block p = peer p's slots for my experts
        inbound = inbound.reshape(ep, e_local, c, d).transpose(1, 0, 2, 3)
        inbound = inbound.reshape(e_local, ep * c, d)
        outbound = expert_ffn(inbound, w1, b1, w2)
        outbound = outbound.reshape(e_local, ep, c, d).transpose(1, 0, 2, 3)
        outbound = outbound.reshape(ep * e_local, c, d)
        ys = lax.all_to_all(
            outbound, expert_axis, split_axis=0, concat_axis=0, tiled=True
        )  # back at the source device, (E, C, d)
    y = jnp.einsum("tec,ecd->td", route.combine.astype(x.dtype), ys)
    return y, route.aux_loss, route.dropped
