"""Mixture-of-experts dispatch: Switch-style top-1 routing + expert-parallel
all-to-all.

The reference has no model parallelism of any kind (SURVEY.md §3 — DP is its
entire point); MoE/EP is a beyond-parity capability of the TPU rebuild, built
the TPU way:

- **static shapes**: routing uses a fixed per-(device, expert) capacity
  ``C = ceil(T_local * capacity_factor / n_experts)``; overflow tokens are
  dropped (their residual path passes through untouched) — the Switch
  Transformer discipline, which keeps every einsum MXU-shaped and lets XLA
  compile one program regardless of routing decisions;
- **dispatch is matmul**: tokens move into expert slots via one-hot
  einsums, not gathers — exactly what the MXU is good at;
- **EP = all_to_all over a mesh axis**: with experts sharded over
  ``expert_axis`` (ep devices x E/ep experts each), one ``lax.all_to_all``
  carries every device's per-expert slot block to the expert's owner and a
  second one brings outputs back — the standard a2a pair riding ICI.

All functions are pure and shard_map-compatible; the dense (no-EP) path is
the oracle the EP path is tested against.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class RouteResult(NamedTuple):
    dispatch: jax.Array  # (T, E, C) one-hot token->slot assignment
    combine: jax.Array  # (T, E, C) dispatch scaled by the router gate
    aux_loss: jax.Array  # scalar Switch load-balancing loss
    dropped: jax.Array  # fraction of (token, choice) ASSIGNMENTS past
    # capacity — denominator k*T, so under top-2 a secondary-only drop
    # contributes half what losing a token entirely would


class RouteIndices(NamedTuple):
    """Index-form routing decision — O(T·k), never materializes (T, E, C)."""

    idx: jax.Array  # (T, k) int32 chosen expert per rank
    slot: jax.Array  # (T, k) int32 queue position within the expert (clamped)
    keep: jax.Array  # (T, k) float32 1.0 iff the assignment fit in capacity
    gates: jax.Array  # (T, k) float32 router gate per kept assignment
    aux_loss: jax.Array  # scalar Switch load-balancing loss
    dropped: jax.Array  # dropped assignments / (k * T)


def switch_route(
    logits: jax.Array, capacity: int
) -> RouteResult:
    """Top-1 (Switch) routing with static capacity.

    ``logits``: (T, E) router scores for T tokens over E experts.
    ``capacity``: max tokens per expert (this device's contribution).
    """
    return topk_route(logits, capacity, k=1)


def route_indices(
    logits: jax.Array, capacity: int, k: int = 1
) -> RouteIndices:
    """Top-k routing with static capacity, in index form (k=1 Switch,
    k=2 GShard).

    Each token is dispatched to its ``k`` highest-scoring experts with gates
    renormalized over the chosen k. Expert queue slots are assigned rank-
    major (every token's primary choice takes slots before any secondary
    choice — the GShard priority discipline), so under capacity pressure
    secondary assignments drop first. ``dropped`` counts dropped
    (token, choice) pairs as a fraction of all ``k * T`` assignments.

    This is the single source of routing truth: both the one-hot einsum
    dispatch (:func:`topk_route`, the small-shape oracle) and the
    scatter/gather dispatch (the large-shape fast path) consume it.
    """
    t, e = logits.shape
    if not 1 <= k <= e:
        raise ValueError(f"need 1 <= k <= {e} experts, got {k}")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, idx = lax.top_k(probs, k)  # (T, k)
    if k == 1:
        gates = gate_vals  # Switch: raw router probability scales the output
    else:
        # GShard: renormalize over the chosen k so the mix sums to 1
        gates = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )
    slots = []
    keeps = []
    kept = jnp.float32(0.0)
    base = jnp.zeros((e,), jnp.float32)  # slots consumed by earlier ranks
    for r in range(k):
        onehot = jax.nn.one_hot(idx[:, r], e, dtype=jnp.float32)  # (T, E)
        # position within this rank's queue, offset by earlier ranks' fill
        within = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (T, E)
        pos_t = (within + base[None, :] * onehot).sum(axis=-1)  # (T,)
        keep = (pos_t < capacity).astype(jnp.float32)
        slots.append(jnp.minimum(pos_t, capacity - 1).astype(jnp.int32))
        keeps.append(keep)
        kept = kept + keep.sum()
        base = base + onehot.sum(axis=0)
    # Switch/GShard aux loss on the PRIMARY assignment: E * sum_e f_e * P_e
    primary = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(primary.mean(axis=0) * probs.mean(axis=0))
    dropped = 1.0 - kept / (k * t)
    return RouteIndices(
        idx,
        jnp.stack(slots, axis=1),
        jnp.stack(keeps, axis=1),
        gates,
        aux,
        dropped,
    )


def _dense_route_from_indices(
    r: RouteIndices, n_experts: int, capacity: int
) -> tuple[jax.Array, jax.Array]:
    """(T, E, C) one-hot dispatch/combine tensors from index-form routing."""
    t, k = r.idx.shape
    dispatch = jnp.zeros((t, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((t, n_experts, capacity), jnp.float32)
    for rank in range(k):
        d_r = (
            jax.nn.one_hot(
                r.idx[:, rank], n_experts, dtype=jnp.float32
            )[:, :, None]
            * jax.nn.one_hot(r.slot[:, rank], capacity)[:, None, :]
            * r.keep[:, rank, None, None]
        )  # (T, E, C)
        dispatch = dispatch + d_r
        combine = combine + d_r * r.gates[:, rank, None, None]
    return dispatch, combine


def topk_route(logits: jax.Array, capacity: int, k: int = 2) -> RouteResult:
    """Dense (T, E, C) one-hot form of :func:`route_indices` — the oracle
    the scatter path is tested against; only viable at small T·E·C."""
    _, e = logits.shape
    r = route_indices(logits, capacity, k)
    dispatch, combine = _dense_route_from_indices(r, e, capacity)
    return RouteResult(dispatch, combine, r.aux_loss, r.dropped)


def dispatch_scatter(
    x: jax.Array, route: RouteIndices, n_experts: int, capacity: int
) -> jax.Array:
    """Move tokens into expert slots by scatter-add: (T, d) -> (E, C, d).

    Slot positions are unique per (expert, slot) by construction (rank-major
    cumulative fill), so the scatter has no collisions; dropped assignments
    are sent to an out-of-range index and discarded by ``mode="drop"``.
    O(T·k·d) memory traffic vs the einsum path's 2·T·E·C·d FLOPs — the
    difference between ~0.7 TFLOP and ~64 MB per layer at the flagship
    bench shape (T=16384, E=8, C=2560, d=1024).
    """
    t, d = x.shape
    k = route.idx.shape[1]
    flat = route.idx * capacity + route.slot  # (T, k)
    oob = jnp.int32(n_experts * capacity)
    tgt = jnp.where(route.keep > 0, flat.astype(jnp.int32), oob)
    src = jnp.broadcast_to(x[:, None, :], (t, k, d)).reshape(t * k, d)
    slots = jnp.zeros((n_experts * capacity, d), x.dtype)
    slots = slots.at[tgt.reshape(-1)].add(src, mode="drop")
    return slots.reshape(n_experts, capacity, d)


def combine_gather(
    ys: jax.Array, route: RouteIndices, capacity: int
) -> jax.Array:
    """Bring expert outputs back to their tokens: (E, C, d) -> (T, d).

    The gather transpose of :func:`dispatch_scatter`; each token mixes its
    k kept slots weighted by the router gates (dropped assignments carry
    weight 0, so the clamped out-of-range gather contributes nothing).
    """
    e, c, d = ys.shape
    flat = ys.reshape(e * c, d)
    tgt = (route.idx * capacity + route.slot).astype(jnp.int32)  # (T, k)
    g = jnp.take(
        flat, tgt.reshape(-1), axis=0, mode="clip"
    ).reshape(*tgt.shape, d)  # (T, k, d)
    w = (route.gates * route.keep).astype(ys.dtype)
    return (g * w[..., None]).sum(axis=1)


def expert_ffn(xs: jax.Array, w1, b1, w2) -> jax.Array:
    """Batched per-expert 2-layer MLP: (E_local, N, d) -> (E_local, N, d)."""
    h = jnp.einsum("end,edh->enh", xs, w1) + b1[:, None, :]
    h = jax.nn.gelu(h)
    return jnp.einsum("enh,ehd->end", h, w2)


def moe_dispatch_compute(
    x: jax.Array,
    router_w: jax.Array,
    w1: jax.Array,
    b1: jax.Array,
    w2: jax.Array,
    *,
    n_experts: int,
    capacity_factor: float = 1.25,
    expert_axis: str | None = None,
    router_topk: int = 1,
    seq_axis: str | None = None,
    dispatch_impl: str = "auto",
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Route ``x`` (T, d) through the expert MLPs; returns (y, aux, dropped).

    Expert weights are LOCAL shards: ``w1`` is (E/ep, d, hidden) when
    ``expert_axis`` names an ep-sized mesh axis (run inside shard_map), or the
    full (E, d, hidden) dense form when ``expert_axis`` is None.
    ``router_topk``: 1 = Switch, 2 = GShard top-2 (capacity scales with k so
    the same capacity_factor means the same slack per assignment).
    ``seq_axis``: under sequence parallelism the aux statistics (fraction
    routed, mean router prob) are psum-averaged over the seq shards, so the
    load-balancing loss is computed over the GLOBAL token population — the
    bilinear E·Σf·p of per-shard means would depend on the partition.
    ``dispatch_impl``: ``"einsum"`` moves tokens via (T, E, C) one-hot
    matmuls (the original GShard form — MXU-shaped but O(T·E·C·d) FLOPs and
    a materialized (T, E, C) tensor), ``"scatter"`` via scatter-add/gather
    (O(T·k·d) traffic), ``"auto"`` picks scatter once the one-hot tensor
    would exceed ~2²² elements. Both compute the identical routing
    (:func:`route_indices`); they differ only in data movement.
    """
    t = x.shape[0]
    capacity = max(
        1, -(-int(t * capacity_factor) * router_topk // n_experts)
    )
    if dispatch_impl not in ("auto", "einsum", "scatter"):
        raise ValueError(f"unknown {dispatch_impl=}")
    if dispatch_impl == "auto":
        dispatch_impl = (
            "scatter" if t * n_experts * capacity > (1 << 22) else "einsum"
        )
    # routing numerics (softmax/cumsum) stay float32; the heavy einsums below
    # run in x's dtype so bf16 compute flows through the expert path
    logits = x.astype(jnp.float32) @ router_w  # (T, E) — router always full E
    route_idx = route_indices(logits, capacity, k=router_topk)
    aux = route_idx.aux_loss
    if seq_axis is not None:
        probs = jax.nn.softmax(logits, axis=-1)
        primary = jax.nn.one_hot(
            jnp.argmax(probs, axis=-1), n_experts, dtype=jnp.float32
        )
        t_global = lax.psum(jnp.float32(t), seq_axis)
        f = lax.psum(primary.sum(axis=0), seq_axis) / t_global
        p = lax.psum(probs.sum(axis=0), seq_axis) / t_global
        aux = n_experts * jnp.sum(f * p)
    w1, b1, w2 = (w.astype(x.dtype) for w in (w1, b1, w2))
    # tokens -> per-expert slots: (E, C, d)
    if dispatch_impl == "scatter":
        slots = dispatch_scatter(x, route_idx, n_experts, capacity)
    else:
        dispatch, combine = _dense_route_from_indices(
            route_idx, n_experts, capacity
        )
        slots = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    if expert_axis is None:
        ys = expert_ffn(slots, w1, b1, w2)  # dense: all experts local
    else:
        ep = lax.psum(1, expert_axis)
        e_local = n_experts // ep
        c = slots.shape[1]
        d = slots.shape[2]
        # (E, C, d) -> exchange so each device holds ITS experts' slots from
        # every peer: tiled a2a splits dim 0 into ep blocks of e_local
        inbound = lax.all_to_all(
            slots, expert_axis, split_axis=0, concat_axis=0, tiled=True
        )  # (ep * e_local, C, d): block p = peer p's slots for my experts
        inbound = inbound.reshape(ep, e_local, c, d).transpose(1, 0, 2, 3)
        inbound = inbound.reshape(e_local, ep * c, d)
        outbound = expert_ffn(inbound, w1, b1, w2)
        outbound = outbound.reshape(e_local, ep, c, d).transpose(1, 0, 2, 3)
        outbound = outbound.reshape(ep * e_local, c, d)
        ys = lax.all_to_all(
            outbound, expert_axis, split_axis=0, concat_axis=0, tiled=True
        )  # back at the source device, (E, C, d)
    if dispatch_impl == "scatter":
        y = combine_gather(ys, route_idx, capacity)
    else:
        y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), ys)
    return y, aux, route_idx.dropped
