"""Single-device attention cores: blockwise (flash-style) + kernel dispatch.

Dense attention materializes the (B, H, T, T) score matrix — ~1 GB per layer
at T=2048/B=8/H=8 fp32 — so every long-context path that lands on ONE device
(the sp=1 fast path of ring attention, and Ulysses' full-sequence local core)
was HBM-bound on score traffic, not FLOPs. Two fixes, dispatched by
:func:`local_attention`:

- :func:`blockwise_attention` — portable memory-efficient attention: an
  online-softmax ``lax.scan`` over K/V blocks (the same recurrence ring
  attention runs across devices, applied within one device), with
  ``jax.checkpoint`` on the block step so autodiff RECOMPUTES block scores in
  the backward pass instead of saving them — O(T·block) live memory for
  forward+backward instead of O(T^2).
- the Pallas TPU flash-attention kernel (``jax.experimental.pallas.ops``) when
  running on a real TPU backend and the shape fits its tiling — the fused
  MXU kernel, used for both forward and backward via its custom VJP.

Measured on v5e: the 4-layer LM step (B=8, H=8, T=2048, D=32) went from
786 ms/step dense to 85 ms/step on the flash path with bf16 activations
(BENCHMARKS.md).

Convention for a query row with NO visible keys (fully-causal-masked or
all-padding window): the output row is zero — masked positions contribute
exactly nothing (``online_softmax_update``), unlike a dense softmax which
would fall back to a uniform average of whatever it was given.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from akka_allreduce_tpu.ops.ring_attention import (
    attention_reference,
    online_softmax_update,
)

# dense is fine (and fastest) below this sequence length: the score block
# fits comfortably in VMEM-scale working sets
_DENSE_MAX_T = 512


def _blockwise_olm(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    scale: float,
    q_offset,
    k_offset,
    block_k: int,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    vary_axes: tuple = (),
):
    """Blockwise online-softmax PARTIALS ``(o, l, m)`` over a local K/V
    slice — the un-normalized core of :func:`blockwise_attention`, also
    the memory-safe local stage of the seq-sharded split-K merge.

    With ``k_scale``/``v_scale`` (int8 cache), ``k``/``v`` are int8
    payloads dequantized ONE BLOCK AT A TIME inside the scan — live
    full-precision memory stays O(block), never the whole slice.
    ``vary_axes``: mesh axes the K/V slice is device-varying over when
    called inside ``shard_map`` — the scan's zero-initialized carry must
    be pcast to match, or the vma typecheck rejects the loop.
    """
    from akka_allreduce_tpu.ops.ring_attention import _MASK_VALUE, repeat_kv

    h = q.shape[2]
    if k.shape[2] != h:  # grouped-query K/V expand at compute
        group = h // k.shape[2]
        k, v = repeat_kv(k, h), repeat_kv(v, h)
        if k_scale is not None:
            k_scale = jnp.repeat(k_scale, group, axis=2)
            v_scale = jnp.repeat(v_scale, group, axis=2)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    nb = -(-tk // block_k)
    pad = nb * block_k - tk
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # (nb, B, block, H, D) so scan carries one block per step
    kb = kp.reshape(b, nb, block_k, h, d).transpose(1, 0, 2, 3, 4)
    vb = vp.reshape(b, nb, block_k, h, d).transpose(1, 0, 2, 3, 4)
    blk = (jnp.arange(nb), kb, vb)
    if k_scale is not None:
        sb = lambda s: jnp.pad(s, ((0, 0), (0, pad), (0, 0))).reshape(  # noqa: E731
            b, nb, block_k, h
        ).transpose(1, 0, 2, 3)
        blk = blk + (sb(k_scale), sb(v_scale))

    qf = q.astype(jnp.float32)
    q_pos = q_offset + jnp.arange(tq)

    def block_step(olm, blk):
        if k_scale is not None:
            idx, kk, vv, ks, vs = blk
            kk = kk.astype(jnp.float32) * ks[..., None]
            vv = vv.astype(jnp.float32) * vs[..., None]
        else:
            idx, kk, vv = blk
        k_pos = k_offset + idx * block_k + jnp.arange(block_k)
        valid = k_pos < k_offset + tk  # mask the zero-padding tail
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (tq, block_k))
        return online_softmax_update(olm, qf, kk, vv, scale, valid), None

    o0 = jnp.zeros((b, h, tq, d), jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    m0 = jnp.full((b, h, tq), _MASK_VALUE, jnp.float32)
    if vary_axes:
        o0, l0, m0 = (
            lax.pcast(x, vary_axes, to="varying") for x in (o0, l0, m0)
        )
    # checkpoint: backward recomputes each block's scores instead of storing
    # them — this is what keeps live memory O(T * block) through autodiff
    step = jax.checkpoint(block_step)
    (o, l, m), _ = lax.scan(step, (o0, l0, m0), blk)
    return o, l, m


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
    block_k: int = 512,
) -> jax.Array:
    """Memory-efficient attention over K/V blocks; same result as
    :func:`attention_reference` to float tolerance.

    Shapes: ``q`` (B, Tq, H, D); ``k``/``v`` (B, Tk, H, D). Offsets position
    the local windows globally for causal masking (as in ring attention).
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    o, l, _ = _blockwise_olm(
        q, k, v, causal=causal, scale=scale,
        q_offset=q_offset, k_offset=k_offset, block_k=block_k,
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def flash_shapes_ok(t: int, d: int) -> bool:
    """Would the Pallas TPU flash kernel accept (T=t, head_dim=d)?

    Conservative static gate (the kernel tiles T in 128-row blocks); also the
    question trainers ask to decide whether shard_map's vma check must be
    relaxed (the kernel's outputs carry no varying-axes annotation).
    """
    return t > _DENSE_MAX_T and t % 512 == 0 and d % 32 == 0


def flash_vma_relax(
    seq_len: int, head_dim: int, *, sp: int = 1, seq_impl: str = "ring"
) -> bool:
    """True when the Pallas flash kernel CAN dispatch inside a trainer's
    step for this attention configuration on this backend. shard_map
    callers must then set ``check_vma=False``: the kernel's outputs carry
    no varying-axes annotation, so the static replication checker cannot
    type them (the trainers' shared gate — LongContext/MoE/Pipeline/FSDP).

    A FULL single-device attention runs at the whole ``seq_len`` when the
    sequence is unsharded (``sp == 1``) or under Ulysses (the all-to-all
    reassembles full T locally); ring attention never runs one, so flash
    never dispatches there.
    """
    local_t = seq_len if (sp == 1 or seq_impl == "ulysses") else 0
    return (
        jax.default_backend() == "tpu"
        and local_t > 0
        and flash_shapes_ok(local_t, head_dim)
    )


def _flash_ok(q: jax.Array, k: jax.Array, q_offset, k_offset) -> bool:
    """Shape/placement gate for the Pallas TPU flash kernel."""
    from akka_allreduce_tpu.ops._platform import interpret_default

    if interpret_default(q, k):
        return False
    if not (isinstance(q_offset, int) and q_offset == 0):
        return False
    if not (isinstance(k_offset, int) and k_offset == 0):
        return False
    b, tq, h, d = q.shape
    return tq == k.shape[1] and flash_shapes_ok(tq, d)


def _scaled_masked_scores(q, k, k_scale, scale, q_offset, k_offset):
    """f32 (B, H, Tq, L) causally-masked scores of ``q`` against a local
    K slice: GQA heads repeat at the compute site, and (for an int8
    cache) the per-row scales fold into the scores (q·(k·s) = (q·k)·s) so
    no dequantized copy of the slice is materialized. THE one copy of the
    score/mask convention for the dense cache-attention paths
    (:func:`quantized_cache_attention`, :func:`seq_decode_attention`)."""
    from akka_allreduce_tpu.ops.ring_attention import _MASK_VALUE, repeat_kv

    h = q.shape[2]
    kc = repeat_kv(k.astype(q.dtype), h)  # convert fuses into the dot
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kc, preferred_element_type=jnp.float32
    )
    if k_scale is not None:
        ks = jnp.repeat(k_scale, h // k.shape[2], axis=2)  # (B, L, H)
        scores = scores * (ks.transpose(0, 2, 1)[:, :, None, :] * scale)
    else:
        scores = scores * scale
    q_pos = q_offset + jnp.arange(q.shape[1])
    k_pos = k_offset + jnp.arange(k.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]
    return jnp.where(mask[None, None], scores, _MASK_VALUE)


def _weighted_v(p, v, v_scale):
    """(B, H, Tq, L) weights × local V slice -> (B, H, Tq, D) f32, with
    int8 row scales folded into the weights (Σ p·s·v = (p·s)·v); the
    sibling of :func:`_scaled_masked_scores` for the V side."""
    from akka_allreduce_tpu.ops.ring_attention import repeat_kv

    h = p.shape[1]
    vc = repeat_kv(v, h)
    if v_scale is not None:
        vs = jnp.repeat(v_scale, h // v.shape[2], axis=2)
        p = p * vs.transpose(0, 2, 1)[:, :, None, :]
    return jnp.einsum(
        "bhqk,bkhd->bhqd",
        p.astype(jnp.float32),
        vc.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )


def quantized_cache_attention(
    q: jax.Array,
    k_q: jax.Array,
    k_scale: jax.Array,
    v_q: jax.Array,
    v_scale: jax.Array,
    *,
    q_offset,
    sm_scale: float | None = None,
) -> jax.Array:
    """Causal attention over an int8-quantized KV cache WITHOUT
    materializing the dequantized cache: per-row scales fold into the
    score matrix and the probability weights (see
    :func:`_scaled_masked_scores` / :func:`_weighted_v`), so the only
    full-cache reads are the int8 payloads — the bandwidth the
    quantization was bought for.

    Shapes: ``q`` (B, Tq, H, D); ``k_q``/``v_q`` (B, L, H_kv, D) int8 with
    (B, L, H_kv) f32 scales. Built for the decode shape (small Tq over a
    long cache); scores are (B, H, Tq, L) — tiny for Tq of a few.
    """
    import math as _math

    scale = sm_scale if sm_scale is not None else 1.0 / _math.sqrt(q.shape[-1])
    scores = _scaled_masked_scores(q, k_q, k_scale, scale, q_offset, 0)
    probs = jax.nn.softmax(scores, axis=-1)
    out = _weighted_v(probs, v_q, v_scale)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def seq_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    q_offset,
    k_offset,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    sm_scale: float | None = None,
    extra_vary_axes: tuple = (),
) -> jax.Array:
    """Decode attention over a SEQUENCE-SHARDED KV cache (VERDICT r4 #5).

    ``extra_vary_axes``: further mesh axes the inputs are device-varying
    over (the ``model`` axis under TP-composed decode) — the blockwise
    branch's scan carry must be typed varying over every such axis.

    Each shard holds its (B, L_local, H_kv, D) slice of the cache;
    ``q`` (B, Tq, H, D) is replicated over ``axis_name``. The shard
    computes a dense partial softmax against its local keys (causal vs
    GLOBAL positions: ``k_offset`` is this shard's first cache slot), and
    the partials merge with one ``pmax`` + two ``psum``s — flash-decoding's
    split-K reduction expressed as XLA collectives riding the ICI ring.

    With ``k_scale``/``v_scale`` (int8 cache), ``k``/``v`` are the int8
    payloads and the per-row scales fold into the scores and weights
    exactly like :func:`quantized_cache_attention` — no dequantized copy
    of the local slice is materialized.

    Local partials dispatch on the score-block size like
    :func:`local_attention`: dense for the decode shape (small Tq), the
    blockwise online-softmax scan (:func:`_blockwise_olm`) when a large
    prefill chunk over a long local slice would otherwise materialize
    (B, H, Tq, L_local) f32 scores. Accumulation is float32 throughout —
    the merge must be exact across shards regardless of compute dtype.
    """
    import math as _math

    scale = sm_scale if sm_scale is not None else 1.0 / _math.sqrt(q.shape[-1])
    if q.shape[1] * k.shape[1] <= _DENSE_MAX_T * _DENSE_MAX_T:
        # dense local partial: take the GLOBAL max before exponentiating
        # (one pmax), so every shard's p uses the same reference — the
        # same rounding as a single-device softmax
        scores = _scaled_masked_scores(
            q, k, k_scale, scale, q_offset, k_offset
        )
        m_g = lax.pmax(jnp.max(scores, axis=-1), axis_name)  # (B, H, Tq)
        p = jnp.exp(scores - m_g[..., None])  # masked slots: exp(-huge)=0
        l_g = lax.psum(jnp.sum(p, axis=-1), axis_name)
        o_g = lax.psum(_weighted_v(p, v, v_scale), axis_name)
    else:
        # blockwise local partials (large prefill chunk x long slice):
        # each shard's (o, l, m) rescale to the global max at merge time
        o, l, m = _blockwise_olm(
            q, k, v, causal=True, scale=scale,
            q_offset=q_offset, k_offset=k_offset, block_k=512,
            k_scale=k_scale, v_scale=v_scale,
            vary_axes=(axis_name,) + tuple(extra_vary_axes),
        )
        m_g = lax.pmax(m, axis_name)
        corr = jnp.exp(m - m_g)
        l_g = lax.psum(l * corr, axis_name)
        o_g = lax.psum(o * corr[..., None], axis_name)
    out = o_g / jnp.maximum(l_g, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def local_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
) -> jax.Array:
    """Best single-device attention for the shape/backend at hand.

    Dispatch: dense for short sequences (fastest, fits on chip), the Pallas
    TPU flash kernel when on TPU with kernel-friendly shapes, else the
    portable blockwise path. All three agree with the dense oracle.

    Grouped-query K/V (fewer heads than ``q``) expand here — the compute
    site; sequence-parallel wires upstream keep the compact form.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if k.shape[2] != q.shape[2]:
        from akka_allreduce_tpu.ops.ring_attention import repeat_kv

        k, v = repeat_kv(k, q.shape[2]), repeat_kv(v, q.shape[2])
    # dense is gated on the SCORE MATRIX size, not the raw lengths: a
    # short query block over a long K/V (the decode-over-cache shape,
    # Tq=1) has a tiny (B, H, Tq, Tk) score tensor, and the blockwise
    # scan would be pure launch overhead for it
    if q.shape[1] * k.shape[1] <= _DENSE_MAX_T * _DENSE_MAX_T:
        return attention_reference(
            q, k, v, causal=causal, sm_scale=scale,
            q_offset=q_offset, k_offset=k_offset,
        )
    if _flash_ok(q, k, q_offset, k_offset):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention,
        )

        # The kernel's DEFAULT 128-row tiling runs ~10 TF/s on v5e at the
        # flagship shape (B8 H16 T2048 D128) — each tiny grid step re-reads
        # its K/V slabs from HBM. 512x512 blocks hit 191 TF/s (measured
        # sweep, BENCHMARKS.md "attention kernel tuning": 128->39.8ms,
        # 256->14.2, 512->2.15, 1024->6.3 per fwd+bwd layer), i.e. the MXU
        # matmul plateau. flash_shapes_ok guarantees T % 512 == 0.
        b = 512
        bs = BlockSizes(
            block_q=b, block_k_major=b, block_k=b, block_b=1,
            block_q_major_dkv=b, block_k_major_dkv=b, block_k_dkv=b,
            block_q_dkv=b, block_k_major_dq=b, block_k_dq=b, block_q_dq=b,
        )
        out = flash_attention(
            q.transpose(0, 2, 1, 3),  # (B, H, T, D) kernel layout
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=causal,
            sm_scale=scale,
            block_sizes=bs,
        )
        return out.transpose(0, 2, 1, 3).astype(q.dtype)
    return blockwise_attention(
        q, k, v, causal=causal, sm_scale=scale,
        q_offset=q_offset, k_offset=k_offset,
    )
