"""Fused single-chip threshold-reduce kernels.

The engine-unit-mode hot op: K worker payloads stacked as ``(K, data)`` are
masked-summed, counted, divided, and (optionally) folded back into every
replica — the reference's ``ScatteredDataBuffer.reduce`` + consumer divide +
``ElasticAverageBinder`` apply (SURVEY.md §3), executed on-chip.

Why Pallas: XLA lowers ``avg = (X*V).sum(0)/c; X' = (1-a)X + a*avg`` to two
passes over ``X`` in HBM (the column average is a full reduction, so the
update cannot start until it finishes — globally). Per column *tile* the
dependency is local, so one kernel pass reads a ``(K, tr, 128)`` tile,
reduces it, and applies the update before moving on: 1 read + 1 write of X
instead of 2 reads + 1 write.

Two measured-on-v5e (64M floats, K=8) details make that theory hold in
practice — without them the fused kernel LOSES to XLA's unfused lowering:

- **accumulator loop over K**, not ``(x * v).sum(0)``: the broadcasted
  multiply materializes a full (K, tr, 128) intermediate on the kernel's
  VMEM stack (OOMs the 16M scoped limit at tr=1024) and its write+re-read
  halves throughput;
- **input/output aliasing** (``input_output_aliases={0: 0}``): lets Mosaic
  reuse the input tile's VMEM and skip a separate output allocation per grid
  step. Measured: 2.45 ms/iter unaliased -> 0.82 ms/iter aliased
  (~625 GB/s of HBM traffic, ~76% of v5e peak; XLA's lowering: 1.29 ms).

The same kernels run under the Pallas TPU interpreter on the CPU test
backend; numeric oracle is numpy masked-sum/count (tests/test_local_reduce.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
_DEF_ROWS = 512  # 512*128 fp32 = 256 KB per K-slice tile (measured sweet spot)


from akka_allreduce_tpu.ops._platform import interpret_default as _interpret_default


def _pad_to_tiles(x: jax.Array, rows: int) -> tuple[jax.Array, int]:
    """(K, data) -> (K, n_tiles*rows, LANE), zero-padded."""
    k, data = x.shape
    tile_elems = rows * LANE
    n_tiles = max(1, -(-data // tile_elems))
    padded = n_tiles * tile_elems
    x = jnp.pad(x, ((0, 0), (0, padded - data)))
    return x.reshape(k, n_tiles * rows, LANE), n_tiles


def _masked_total(x_ref, v):
    """sum_k v[k] * x[k] as an accumulator loop: no (K, tr, LANE) stack
    intermediate (VMEM-stack OOM at large tiles, and an extra pass)."""
    total = x_ref[0] * v[0, 0]
    for k in range(1, x_ref.shape[0]):
        total = total + x_ref[k] * v[k, 0]
    return total


def _avg_kernel(x_ref, v_ref, avg_ref, cnt_ref):
    # x: (K, rows, LANE) tile; v: (K, 1); avg: (rows, LANE)
    v = v_ref[:]
    count = jnp.sum(v)
    cnt_ref[0, 0] = count
    avg_ref[:] = _masked_total(x_ref, v) / jnp.maximum(count, 1.0)


@functools.partial(jax.jit, static_argnames=("rows", "interpret"))
def _masked_average_impl(x, valid, *, rows: int, interpret: bool):
    k, data = x.shape
    xt, n_tiles = _pad_to_tiles(x, rows)
    v2 = valid.reshape(k, 1).astype(x.dtype)
    avg, cnt = pl.pallas_call(
        _avg_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (k, rows, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((k, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_tiles * rows, LANE), x.dtype),
            jax.ShapeDtypeStruct((1, 1), x.dtype),
        ],
        interpret=interpret,
    )(xt, v2)
    return avg.reshape(-1)[:data], cnt[0, 0]


def masked_average(
    x: jax.Array,
    valid: jax.Array,
    *,
    rows: int = _DEF_ROWS,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One-pass threshold reduce of K stacked payloads.

    Args:
      x: ``(K, data)`` float payloads (one row per virtual worker).
      valid: ``(K,)`` 0/1 contribution mask.
    Returns:
      ``(avg, count)``: ``avg[i] = sum_k v_k x_k[i] / max(count, 1)``.
    """
    if interpret is None:
        interpret = _interpret_default(x)
    return _masked_average_impl(
        x, valid, rows=rows, interpret=bool(interpret)
    )


def _elastic_kernel(x_ref, v_ref, alpha_ref, out_ref):
    v = v_ref[:]  # (K, 1)
    alpha = alpha_ref[0]
    count = jnp.sum(v)
    avg = _masked_total(x_ref, v) / jnp.maximum(count, 1.0)
    # count == 0: nobody contributed this round; replicas keep their state
    # (binder/elastic.py semantics — counts>0 gates the update)
    keep = jnp.where(count > 0.0, 1.0 - alpha, 1.0).astype(x_ref.dtype)
    pull = jnp.where(count > 0.0, alpha, 0.0).astype(x_ref.dtype)
    for k in range(x_ref.shape[0]):
        out_ref[k] = keep * x_ref[k] + pull * avg


@functools.partial(jax.jit, static_argnames=("rows", "interpret"))
def _elastic_step_tiled_impl(xt, valid, alpha, *, rows: int, interpret: bool):
    k, total_rows, _ = xt.shape
    n_tiles = total_rows // rows
    v2 = valid.reshape(k, 1).astype(xt.dtype)
    a = jnp.asarray(alpha, xt.dtype).reshape(1)
    return pl.pallas_call(
        _elastic_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (k, rows, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((k, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (k, rows, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(xt.shape, xt.dtype),
        # x' overwrites x: reuses the tile's VMEM and skips the output
        # allocation per grid step — the single biggest measured win (3x)
        input_output_aliases={0: 0},
        interpret=interpret,
    )(xt, v2, a)


def pack_tiles(x: jax.Array, rows: int = _DEF_ROWS) -> jax.Array:
    """(K, data) -> (K, T, LANE) for the tiled fast path (pads data to a
    multiple of rows*LANE). Pack ONCE, carry the tiled array through the
    iteration loop, unpack at the end with :func:`unpack_tiles`."""
    return _pad_to_tiles(x, rows)[0]


def unpack_tiles(xt: jax.Array, data: int) -> jax.Array:
    """(K, T, LANE) -> (K, data): inverse of :func:`pack_tiles`."""
    return xt.reshape(xt.shape[0], -1)[:, :data]


def elastic_average_step(
    x: jax.Array,
    valid: jax.Array,
    alpha: float | jax.Array,
    *,
    rows: int = _DEF_ROWS,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused elastic-averaging round over K local replicas, one HBM pass.

    ``x' = (1-alpha) * x + alpha * avg`` where ``avg`` is the threshold-masked
    contributor average; if no replica contributed (``sum(valid) == 0``) the
    state is returned unchanged. Shapes as :func:`masked_average`, plus a
    pre-tiled ``(K, T, LANE)`` form (see :func:`pack_tiles`).

    The input ``x`` is donated (input/output aliased) on the TPU path; callers
    must not reuse it after the call. **Iteration loops should carry the
    pre-tiled form**: the internal (K, data) <-> (K, T, LANE) reshape defeats
    XLA's alias analysis across a ``fori_loop`` carry, re-introducing the
    copies that aliasing exists to remove (measured 3x on v5e — see module
    docstring).
    """
    if interpret is None:
        interpret = _interpret_default(x)
    if x.ndim == 3:
        if x.shape[2] != LANE or x.shape[1] % rows:
            raise ValueError(
                f"tiled input must be (K, m*{rows}, {LANE}), got {x.shape}"
            )
        return _elastic_step_tiled_impl(
            x, valid, alpha, rows=rows, interpret=bool(interpret)
        )
    out = _elastic_step_tiled_impl(
        pack_tiles(x, rows), valid, alpha, rows=rows, interpret=bool(interpret)
    )
    return unpack_tiles(out, x.shape[1])
