"""Fused single-chip threshold-reduce kernels.

The engine-unit-mode hot op: K worker payloads stacked as ``(K, data)`` are
masked-summed, counted, divided, and (optionally) folded back into every
replica — the reference's ``ScatteredDataBuffer.reduce`` + consumer divide +
``ElasticAverageBinder`` apply (SURVEY.md §3), executed on-chip.

Why Pallas: XLA lowers ``avg = (X*V).sum(0)/c; X' = (1-a)X + a*avg`` to two
passes over ``X`` in HBM (the column average is a full reduction, so the
update cannot start until it finishes — globally). Per column *tile* the
dependency is local, so one kernel pass reads a ``(K, tr, 128)`` tile,
reduces it, and applies the update before moving on: 1 read + 1 write of X
instead of 2 reads + 1 write. On HBM-bound sizes that is the difference
between ~1/3 and ~1/2 of peak bandwidth on the bench's headline op.

The same kernels run under the Pallas TPU interpreter on the CPU test
backend; numeric oracle is numpy masked-sum/count (tests/test_ops.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
_DEF_ROWS = 512  # 512*128 fp32 = 256 KB per K-slice tile


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to_tiles(x: jax.Array, rows: int) -> tuple[jax.Array, int]:
    """(K, data) -> (K, n_tiles*rows, LANE), zero-padded."""
    k, data = x.shape
    tile_elems = rows * LANE
    n_tiles = max(1, -(-data // tile_elems))
    padded = n_tiles * tile_elems
    x = jnp.pad(x, ((0, 0), (0, padded - data)))
    return x.reshape(k, n_tiles * rows, LANE), n_tiles


def _avg_kernel(x_ref, v_ref, avg_ref, cnt_ref):
    # x: (K, rows, LANE) tile; v: (K, 1) in SMEM-ish vmem; avg: (rows, LANE)
    v = v_ref[:]  # (K, 1)
    masked = x_ref[:] * v[:, :, None]
    total = jnp.sum(masked, axis=0)
    count = jnp.sum(v)
    cnt_ref[0, 0] = count
    avg_ref[:] = total / jnp.maximum(count, 1.0)


@functools.partial(jax.jit, static_argnames=("rows", "interpret"))
def _masked_average_impl(x, valid, *, rows: int, interpret: bool):
    k, data = x.shape
    xt, n_tiles = _pad_to_tiles(x, rows)
    v2 = valid.reshape(k, 1).astype(x.dtype)
    avg, cnt = pl.pallas_call(
        _avg_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (k, rows, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((k, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_tiles * rows, LANE), x.dtype),
            jax.ShapeDtypeStruct((1, 1), x.dtype),
        ],
        interpret=interpret,
    )(xt, v2)
    return avg.reshape(-1)[:data], cnt[0, 0]


def masked_average(
    x: jax.Array,
    valid: jax.Array,
    *,
    rows: int = _DEF_ROWS,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One-pass threshold reduce of K stacked payloads.

    Args:
      x: ``(K, data)`` float payloads (one row per virtual worker).
      valid: ``(K,)`` 0/1 contribution mask.
    Returns:
      ``(avg, count)``: ``avg[i] = sum_k v_k x_k[i] / max(count, 1)``.
    """
    if interpret is None:
        interpret = _interpret_default()
    return _masked_average_impl(
        x, valid, rows=rows, interpret=bool(interpret)
    )


def _elastic_kernel(x_ref, v_ref, alpha_ref, out_ref):
    v = v_ref[:]  # (K, 1)
    alpha = alpha_ref[0]
    masked = x_ref[:] * v[:, :, None]
    count = jnp.sum(v)
    avg = jnp.sum(masked, axis=0) / jnp.maximum(count, 1.0)
    # count == 0: nobody contributed this round; replicas keep their state
    # (binder/elastic.py semantics — counts>0 gates the update)
    keep = jnp.where(count > 0.0, 1.0 - alpha, 1.0).astype(x_ref.dtype)
    pull = jnp.where(count > 0.0, alpha, 0.0).astype(x_ref.dtype)
    out_ref[:] = keep * x_ref[:] + pull * avg[None]


@functools.partial(jax.jit, static_argnames=("rows", "interpret"))
def _elastic_step_impl(x, valid, alpha, *, rows: int, interpret: bool):
    k, data = x.shape
    xt, n_tiles = _pad_to_tiles(x, rows)
    v2 = valid.reshape(k, 1).astype(x.dtype)
    a = jnp.asarray(alpha, x.dtype).reshape(1)
    out = pl.pallas_call(
        _elastic_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (k, rows, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((k, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (k, rows, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(xt.shape, x.dtype),
        interpret=interpret,
    )(xt, v2, a)
    return out.reshape(k, -1)[:, :data]


def elastic_average_step(
    x: jax.Array,
    valid: jax.Array,
    alpha: float | jax.Array,
    *,
    rows: int = _DEF_ROWS,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused elastic-averaging round over K local replicas, one HBM pass.

    ``x' = (1-alpha) * x + alpha * avg`` where ``avg`` is the threshold-masked
    contributor average; if no replica contributed (``sum(valid) == 0``) the
    state is returned unchanged. Shapes as :func:`masked_average`.
    """
    if interpret is None:
        interpret = _interpret_default()
    return _elastic_step_impl(
        x, valid, alpha, rows=rows, interpret=bool(interpret)
    )
