"""Which platform will a Pallas kernel actually lower onto?

``jax.default_backend()`` is the wrong signal whenever data lives on devices
of a *non-default* platform — e.g. a TPU plugin is loaded (default backend
"tpu") but the computation runs on a virtual CPU mesh. Compiled-mode Pallas
TPU kernels then lower onto CPU and fail outright
(``Only interpret mode is supported on CPU backend``).

The reliable signals, in order of preference:

1. the mesh's device platform — callers that hold a ``Mesh`` (comm.allreduce)
   pass ``mesh.devices.flat[0].platform`` explicitly;
2. the platform of a concrete input array's committed device — available for
   the single-device ops (local_reduce, local_attention) when called eagerly;
3. ``jax.default_backend()`` — the only thing left for tracers inside
   ``jit``/``shard_map``; correct whenever the enclosing jit targets the
   default platform (which the test conftest and dryrun guarantee by forcing
   ``jax_platforms=cpu`` before any backend touch).
"""

from __future__ import annotations

import jax


def data_platform(*arrays) -> str:
    """Platform the given arrays live on, else the default backend."""
    for x in arrays:
        devices_fn = getattr(x, "devices", None)
        if devices_fn is None:
            continue  # numpy input: no device
        try:
            devs = devices_fn()
        except Exception:
            continue  # tracer: .devices exists but raises when called
        if devs:
            return next(iter(devs)).platform
    return jax.default_backend()


def interpret_default(*arrays) -> bool:
    """True when a Pallas TPU kernel must run in interpret mode."""
    return data_platform(*arrays) != "tpu"
