"""Sequence/context parallelism: ring attention and all-to-all (Ulysses).

The reference has no long-context support at all (SURVEY.md §6 "Long-context /
sequence parallelism: ABSENT") — this module is where the TPU rebuild goes
beyond parity. Both strategies shard the *sequence* dimension across a mesh
axis so attention over sequences far larger than one chip's HBM runs at full
MXU utilization:

- :func:`ring_attention` — blockwise attention with online (flash-style)
  softmax. Each device keeps its Q shard resident and rotates K/V shards
  around the ICI ring via ``lax.ppermute``, accumulating ``(m, l, o)`` running
  statistics. Communication is the same neighbor-ring schedule as the
  framework's ring allreduce (ops/ring.py), so it rides ICI links the same
  way; compute per step is a dense (T_local x T_local) block that XLA tiles
  onto the MXU.
- :func:`ulysses_attention` — DeepSpeed-Ulysses-style: two ``lax.all_to_all``
  collectives re-shard from sequence-parallel to head-parallel, run full-
  sequence attention on ``H / n`` heads per device, and re-shard back. Cheaper
  in collective steps (2 vs n-1) when heads divide evenly; ring wins when
  H < n or when overlap with the MXU matters.

Both are pure functions to call INSIDE ``shard_map`` with the sequence mesh
axis name, and both match the dense :func:`attention_reference` oracle to
float tolerance (tests/test_ring_attention.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

# Finite stand-in for -inf: keeps the online-softmax recurrence NaN-free when
# an entire (causal-masked) block is invisible to a query row — the bogus
# exp(0)=1 contributions such a block accumulates are wiped by the correction
# factor exp(m_old - m_new) = 0 the moment a real block arrives, and every row
# sees at least its own diagonal block, so the final (l, o) are exact.
_MASK_VALUE = -1e30


def repeat_kv(k: jax.Array, n_heads: int) -> jax.Array:
    """Expand grouped-query K/V (B, T, H_kv, D) to ``n_heads`` by repeating
    each KV head over its query group (GQA; H_kv == 1 is MQA). The repeat
    happens at the COMPUTE site only — sequence-parallel schedules move the
    compact (B, T, H_kv, D) form over the wire, which is where GQA's
    bandwidth saving lives."""
    h_kv = k.shape[2]
    if h_kv == n_heads:
        return k
    if n_heads % h_kv:
        raise ValueError(f"{n_heads=} not divisible by kv heads {h_kv}")
    return jnp.repeat(k, n_heads // h_kv, axis=2)


def online_softmax_update(olm, qf, kk, vv, scale, mask):
    """One flash-style block fold: merge K/V block (kk, vv) into the running
    ``(o, l, m)`` statistics for queries ``qf`` (all fp32).

    ``mask``: boolean (Tq, Tk_block) visibility, or None for a fully visible
    block. Masked positions contribute EXACTLY zero — including the corner
    case where a whole row has seen nothing yet (m still at the sentinel):
    there ``exp(score - m) = 1`` would otherwise leak mask/padding entries
    into ``l``. Shared by ring attention (cross-device blocks) and the
    single-device blockwise path so the numerically delicate recurrence
    exists once.
    """
    o, l, m = olm
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kk.astype(jnp.float32)) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, _MASK_VALUE)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vv.astype(jnp.float32)
    )
    return o, l, m_new


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
) -> jax.Array:
    """Dense softmax attention; the single-device oracle and the Ulysses core.

    Shapes: ``q`` (B, Tq, H, D); ``k``/``v`` (B, Tk, H, D). Offsets give the
    global positions of the local windows for causal masking under sharding.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """Blockwise ring attention over sequence shards; call inside ``shard_map``.

    ``q``/``k``/``v``: this device's sequence shard, (B, T_local, H, D); the
    global sequence is the concatenation along ``axis_name`` in mesh order
    (``n = lax.axis_size(axis_name)`` shards). Returns this device's
    (B, T_local, H, D) shard of the attention output, exactly as if dense
    attention ran over the full sequence.

    K/V rotate one neighbor per step (device i -> i+1), so after step ``s``
    device ``i`` holds the shard originating at ``(i - s) mod n``; the online
    softmax makes the result order-independent and numerically stable in fp32.
    The last block is consumed outside the loop so no final (discarded)
    rotation crosses the ICI.

    Grouped-query attention: ``k``/``v`` may carry fewer heads than ``q``
    (H_kv dividing H) — the COMPACT form rotates around the ring, so the
    per-step ICI bytes shrink by H/H_kv, and each block expands KV locally
    just before its score matmul (:func:`repeat_kv`).
    """
    n = lax.axis_size(axis_name)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if n == 1:
        from akka_allreduce_tpu.ops.local_attention import local_attention

        return local_attention(q, k, v, causal=causal, sm_scale=scale)
    b, t, h, d = q.shape
    my = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    q_pos = my * t + jnp.arange(t)

    def block_update(olm, src, kk, vv):
        """Fold the K/V shard that originated on device `src` into (o, l, m)."""
        if causal:
            k_pos = src * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        kk, vv = repeat_kv(kk, h), repeat_kv(vv, h)
        return online_softmax_update(olm, qf, kk, vv, scale, mask)

    def step(s, carry):
        o, l, m, kk, vv = carry
        o, l, m = block_update((o, l, m), jnp.mod(my - s, n), kk, vv)
        kk = lax.ppermute(kk, axis_name, fwd)
        vv = lax.ppermute(vv, axis_name, fwd)
        return o, l, m, kk, vv

    # Derive inits from q so they carry q's full device-varying spec (seq axis
    # plus any batch axes of an enclosing 2D mesh); constant zeros would make
    # the fori_loop carry types mismatch (unvarying in, varying out).
    o0 = jnp.swapaxes(qf, 1, 2) * 0.0  # (b, h, t, d)
    l0 = o0[..., 0]  # (b, h, t)
    m0 = l0 + _MASK_VALUE
    o, l, m, kk, vv = lax.fori_loop(0, n - 1, step, (o0, l0, m0, k, v))
    o, l, _ = block_update((o, l, m), jnp.mod(my - (n - 1), n), kk, vv)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """All-to-all sequence parallelism; call inside ``shard_map``.

    Re-shards (B, T/n, H, D) -> (B, T, H/n, D) with one ``all_to_all``, runs
    full-sequence attention on the local head group, and re-shards back.
    Requires ``H % lax.axis_size(axis_name) == 0``.

    Grouped-query attention: when the KV head count also divides the axis
    size, K/V cross the all_to_all in COMPACT form (wire bytes shrink by
    H/H_kv) and the local core expands them; otherwise they are expanded
    before the exchange (correct, no bandwidth saving — noted so callers
    pick H_kv >= the seq-axis size when they want the win).
    """
    from akka_allreduce_tpu.ops.local_attention import local_attention

    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if n == 1:
        return local_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if h % n:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by axis size {n}"
        )
    if k.shape[2] % n:
        k, v = repeat_kv(k, h), repeat_kv(v, h)

    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # full-sequence local core: memory-efficient/flash, not dense
    out = local_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
